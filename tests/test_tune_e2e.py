"""HPO e2e with real worker processes: a random-search experiment whose
trials run the ``objective_probe`` entrypoint as real JAXJob workers —
the katib kind-based e2e analog (SURVEY.md §4.5, §3.3 full stack)."""

import pytest

from kubeflow_tpu.core.tuning import Experiment
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology
from kubeflow_tpu.tune.client import build_experiment, parameter


@pytest.fixture()
def cp(tmp_path):
    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu",
    ))
    plane.start()
    yield plane
    plane.stop()


def test_experiment_end_to_end(cp):
    exp = build_experiment(
        "hpo-e2e",
        entrypoint="objective_probe",
        parameters=[parameter("x", min=-1.0, max=1.0),
                    parameter("y", min=-1.0, max=1.0)],
        objective_metric="objective",
        algorithm="random",
        algorithm_settings={"random_state": 0},
        max_trial_count=3,
        parallel_trial_count=3,
        base_config={"steps": 3},
    )
    cp.submit(exp)
    done = cp.wait_for(exp, "Succeeded", timeout=120)
    assert done.status.trials_succeeded == 3
    opt = done.status.current_optimal_trial
    assert opt.trial_name and opt.objective_value is not None
    # The probe's final objective is exactly the quadratic at the assignment.
    x, y = opt.parameter_assignments["x"], opt.parameter_assignments["y"]
    assert opt.objective_value == pytest.approx(
        (x - 0.3) ** 2 + (y + 0.2) ** 2, abs=1e-6)
