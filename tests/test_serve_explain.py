"""Explainer hop (VERDICT r3 #5 — the kserve predictor/transformer/
explainer triad's third leg): attribution math sanity (finite differences),
the :explain route, and the ISVC spec wiring."""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import decoder_forward, init_decoder_params
from kubeflow_tpu.serve.explain import grad_x_input, leave_one_out


@pytest.fixture(scope="module")
def cfg():
    return preset("tiny", dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


TOKENS = [5, 17, 3, 99, 42, 7]


class TestAttributionMath:
    def test_grad_x_input_matches_finite_difference(self, cfg, params):
        """score_i is the exact directional derivative of the target
        log-prob along e_i: shrinking token i's embedding by epsilon must
        change the log-prob by ~ -epsilon * score_i."""
        out = grad_x_input(TOKENS, params=params, cfg=cfg)
        target = out["target_token"]
        toks = jnp.asarray([TOKENS], jnp.int32)
        embeds = params["embed"].astype(jnp.float32)[toks]

        def lp_of(e):
            logits, _, _ = decoder_forward(params, toks, cfg, inputs_embeds=e)
            return float(jax.nn.log_softmax(logits[0, -1])[target])

        eps = 1e-3
        for i in (0, 3, len(TOKENS) - 1):
            perturbed = embeds.at[0, i].multiply(1.0 - eps)
            fd = (lp_of(embeds) - lp_of(perturbed)) / eps
            assert fd == pytest.approx(out["scores"][i], rel=0.05, abs=1e-3)

    def test_leave_one_out_scores(self, cfg, params):
        """Occlusion scores must equal per-ablation full forwards, and the
        batched [S,S] formulation must agree with doing them one by one."""
        out = leave_one_out(TOKENS, params=params, cfg=cfg)
        assert len(out["scores"]) == len(TOKENS)
        target = out["target_token"]
        for i in (1, 4):
            ablated = list(TOKENS)
            ablated[i] = 0
            logits, _, _ = decoder_forward(
                params, jnp.asarray([ablated], jnp.int32), cfg)
            lp = float(jax.nn.log_softmax(logits[0, -1])[target])
            assert out["scores"][i] == pytest.approx(
                out["target_logprob"] - lp, abs=1e-4)

    def test_handlers_resolve(self):
        from kubeflow_tpu.serve.explain import build_explainer

        assert build_explainer(None) is None
        assert build_explainer({"handler": "grad_x_input"}) is grad_x_input
        with pytest.raises(KeyError, match="not registered"):
            build_explainer({"handler": "nope"})


def _post(url, body, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class TestExplainRoute:
    def test_explain_route_serves_scores(self, cfg, params):
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.serve.engine import LLMEngine
        from kubeflow_tpu.serve.explain import build_explainer
        from kubeflow_tpu.serve.server import ModelServer

        engine = LLMEngine(cfg, BatchingSpec(max_batch_size=2, max_seq_len=64,
                                             prefill_buckets=[16]),
                           params=params)
        server = ModelServer(
            "exp", engine,
            explainer=build_explainer({"handler": "grad_x_input"}))
        server.start()
        try:
            out = _post(server.url + "/v1/models/exp:explain",
                        {"instances": ["hi"]})
            (exp,) = out["explanations"]
            assert exp["method"] == "grad_x_input"
            # byte tokenizer may add BOS: lengths agree, >= the 2 chars
            assert len(exp["scores"]) == len(exp["tokens"]) >= 2
            assert all(np.isfinite(s) for s in exp["scores"])
            assert isinstance(exp["predicted_text"], str)
        finally:
            server.stop()

    def test_overlong_explain_prompt_is_400(self, cfg, params):
        """Attribution is O(S) forwards; an uncapped prompt would OOM the
        live serving chip — reject past the engine's max_seq_len."""
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.serve.engine import LLMEngine
        from kubeflow_tpu.serve.explain import build_explainer
        from kubeflow_tpu.serve.server import ModelServer

        engine = LLMEngine(cfg, BatchingSpec(max_batch_size=2, max_seq_len=32,
                                             prefill_buckets=[16]),
                           params=params)
        server = ModelServer(
            "exp", engine,
            explainer=build_explainer({"handler": "leave_one_out"}))
        server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(server.url + "/v1/models/exp:explain",
                      {"instances": ["x" * 200]})
            assert ei.value.code == 400
            assert "limit" in json.loads(ei.value.read())["error"]
        finally:
            server.stop()

    def test_explain_without_explainer_is_400(self, cfg, params):
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.serve.engine import LLMEngine
        from kubeflow_tpu.serve.server import ModelServer

        engine = LLMEngine(cfg, BatchingSpec(max_batch_size=2, max_seq_len=64,
                                             prefill_buckets=[16]),
                           params=params)
        server = ModelServer("exp", engine)
        server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(server.url + "/v1/models/exp:explain",
                      {"instances": ["hi"]})
            assert ei.value.code == 400
        finally:
            server.stop()


@pytest.mark.slow
def test_isvc_explainer_e2e(tmp_path):
    """ExplainerSpec wired like the transformer hop: an InferenceService
    with an explainer serves :explain through the routed URL."""
    from kubeflow_tpu.core.object import ObjectMeta
    from kubeflow_tpu.core.serving import (
        BatchingSpec, ExplainerSpec, InferenceService, InferenceServiceSpec,
        ModelSpec, PredictorSpec,
    )
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu"))
    plane.start()
    try:
        isvc = plane.submit(InferenceService(
            metadata=ObjectMeta(name="exp"),
            spec=InferenceServiceSpec(
                predictor=PredictorSpec(
                    model=ModelSpec(model_name="exp",
                                    config={"preset": "tiny",
                                            "overrides": {"vocab_size": 512}}),
                    batching=BatchingSpec(max_batch_size=2, max_seq_len=64,
                                          prefill_buckets=[32])),
                explainer=ExplainerSpec(handler="leave_one_out"))))
        ready = plane.wait_for(isvc, "Ready", timeout=240)
        out = _post(ready.status.url + "/v1/models/exp:explain",
                    {"instances": ["hey"]}, timeout=180)
        (exp,) = out["explanations"]
        assert exp["method"] == "leave_one_out"
        assert len(exp["scores"]) == len(exp["tokens"]) >= 3
    finally:
        plane.stop()


class TestShardedExplain:
    """VERDICT r4 next #7: the triad's third leg on the engine's REAL
    configurations — TP-sharded params, MoE models, quantized weights.
    The handlers jit with the engine mesh so GSPMD partitions attribution
    exactly like serving dispatches."""

    def _explain_via_server(self, cfg, params, mesh=None, handler=None):
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.serve.engine import LLMEngine
        from kubeflow_tpu.serve.explain import build_explainer
        from kubeflow_tpu.serve.server import ModelServer

        engine = LLMEngine(cfg, BatchingSpec(max_batch_size=2,
                                             max_seq_len=64,
                                             prefill_buckets=[16]),
                           params=params, mesh=mesh)
        server = ModelServer(
            "exp", engine,
            explainer=build_explainer(
                {"handler": handler or "grad_x_input"}))
        server.start()
        try:
            out = _post(server.url + "/v1/models/exp:explain",
                        {"instances": ["hello"]})
            return out["explanations"][0]
        finally:
            server.stop()

    def test_tp2_scores_match_single_device(self, cfg, params):
        from kubeflow_tpu.runtime.mesh import build_mesh

        mesh = build_mesh({"model": 2}, jax.devices()[:2])
        exp_tp = self._explain_via_server(cfg, params, mesh=mesh)
        exp_1 = self._explain_via_server(cfg, params, mesh=None)
        assert exp_tp["target_token"] == exp_1["target_token"]
        # TP partial-sum rounding differs from the single-device order:
        # scores agree to bf16-accumulation tolerance, not bitwise.
        np.testing.assert_allclose(exp_tp["scores"], exp_1["scores"],
                                   rtol=0.05, atol=1e-3)

    def test_tp2_leave_one_out(self, cfg, params):
        from kubeflow_tpu.runtime.mesh import build_mesh

        mesh = build_mesh({"model": 2}, jax.devices()[:2])
        exp_tp = self._explain_via_server(cfg, params, mesh=mesh,
                                          handler="leave_one_out")
        exp_1 = self._explain_via_server(cfg, params, mesh=None,
                                         handler="leave_one_out")
        assert exp_tp["target_token"] == exp_1["target_token"]
        np.testing.assert_allclose(exp_tp["scores"], exp_1["scores"],
                                   rtol=0.05, atol=1e-3)

    def test_moe_sharded_explain_finite(self):
        """MoE model served TP-sharded: explain resolves dense routing
        (batch-independent) and returns finite scores."""
        from kubeflow_tpu.runtime.mesh import build_mesh

        moe_cfg = preset("tiny-moe", dtype="float32")
        moe_params = init_decoder_params(jax.random.PRNGKey(1), moe_cfg)
        mesh = build_mesh({"model": 2}, jax.devices()[:2])
        exp = self._explain_via_server(moe_cfg, moe_params, mesh=mesh)
        assert all(np.isfinite(s) for s in exp["scores"])
        exp_loo = self._explain_via_server(moe_cfg, moe_params, mesh=mesh,
                                           handler="leave_one_out")
        assert all(np.isfinite(s) for s in exp_loo["scores"])

    def test_quantized_engine_explain(self, cfg, params):
        """int8 weights: grads flow through the dequant to the embeddings;
        scores stay close to the full-precision engine's."""
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.serve.engine import LLMEngine
        from kubeflow_tpu.serve.explain import build_explainer
        from kubeflow_tpu.serve.server import ModelServer

        engine = LLMEngine(
            cfg, BatchingSpec(max_batch_size=2, max_seq_len=64,
                              prefill_buckets=[16], quantize="int8"),
            params=params)
        server = ModelServer(
            "exp", engine,
            explainer=build_explainer({"handler": "grad_x_input"}))
        server.start()
        try:
            out = _post(server.url + "/v1/models/exp:explain",
                        {"instances": ["hello"]})
            exp = out["explanations"][0]
            assert all(np.isfinite(s) for s in exp["scores"])
        finally:
            server.stop()
