"""Object store semantics: CRUD, optimistic concurrency, watch, ownership.

Mirrors the reference's reliance on apiserver semantics (resourceVersion
conflicts, informer list+watch replay) — SURVEY.md §4 pattern (b)."""

import pytest

from kubeflow_tpu.core.jobs import JAXJob, Worker, WorkerSpec, WorkloadSpec
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.store import (
    AlreadyExistsError, ConflictError, EventType, NotFoundError, ObjectStore,
)


def make_worker(name="w0", job="default/tiny", index=0):
    return Worker(
        metadata=ObjectMeta(name=name),
        spec=WorkerSpec(job=job, replica_index=index,
                        template=WorkloadSpec(entrypoint="noop")),
    )


def test_create_get_roundtrip(store, tiny_job):
    created = store.create(tiny_job)
    assert created.metadata.uid
    assert created.metadata.resource_version == 1
    got = store.get(JAXJob, "tiny")
    assert got.spec == tiny_job.spec
    assert got.metadata.creation_timestamp is not None


def test_create_duplicate_fails(store, tiny_job):
    store.create(tiny_job)
    with pytest.raises(AlreadyExistsError):
        store.create(tiny_job)


def test_get_missing_raises(store):
    with pytest.raises(NotFoundError):
        store.get(JAXJob, "nope")
    assert store.try_get(JAXJob, "nope") is None


def test_update_conflict_on_stale_version(store, tiny_job):
    a = store.create(tiny_job)
    b = store.get(JAXJob, "tiny")
    a.spec.replica_specs["worker"].replicas = 4
    a.spec.parallelism.data = 4
    store.update(a)
    b.spec.replica_specs["worker"].replicas = 8
    with pytest.raises(ConflictError):
        store.update(b)


def test_generation_bumps_on_spec_change_only(store, tiny_job):
    a = store.create(tiny_job)
    assert a.metadata.generation == 1
    a.status.set_condition("Created")
    a = store.update_status(a)
    assert a.metadata.generation == 1  # status-only: no generation bump
    a.spec.replica_specs["worker"].template.config["steps"] = 5
    a = store.update(a, check_version=False)
    assert a.metadata.generation == 2


def test_returned_objects_are_copies(store, tiny_job):
    created = store.create(tiny_job)
    created.metadata.labels["mutated"] = "yes"
    assert "mutated" not in store.get(JAXJob, "tiny").metadata.labels


def test_list_with_namespace_and_labels(store):
    for i, ns in enumerate(["a", "a", "b"]):
        w = make_worker(name=f"w{i}")
        w.metadata.namespace = ns
        w.metadata.labels = {"idx": str(i % 2)}
        store.create(w)
    assert len(store.list(Worker)) == 3
    assert len(store.list(Worker, namespace="a")) == 2
    assert len(store.list(Worker, label_selector={"idx": "0"})) == 2


def test_watch_replay_and_live_events(store, tiny_job):
    store.create(tiny_job)
    with store.watch(kinds=["JAXJob"]) as w:
        ev = w.next(timeout=1)
        assert ev.type == EventType.ADDED and ev.object.metadata.name == "tiny"
        job = store.get(JAXJob, "tiny")
        job.status.set_condition("Created")
        store.update_status(job)
        ev = w.next(timeout=1)
        assert ev.type == EventType.MODIFIED
        store.delete(JAXJob, "tiny")
        ev = w.next(timeout=1)
        assert ev.type == EventType.DELETED


def test_watch_kind_filter(store, tiny_job):
    with store.watch(kinds=["Worker"]) as w:
        store.create(tiny_job)
        store.create(make_worker())
        ev = w.next(timeout=1)
        assert ev.object.kind == "Worker"
        assert w.next(timeout=0.05) is None


def test_ownership_cascade_delete(store, tiny_job):
    job = store.create(tiny_job)
    for i in range(3):
        w = make_worker(name=f"tiny-worker-{i}", index=i)
        w.metadata.owner = job.key
        store.create(w)
    assert len(store.list_owned(job)) == 3
    assert store.delete_owned(job) == 3
    assert store.list(Worker) == []


def test_slow_watcher_dropped_without_breaking_writers(tiny_job):
    """Overflowing a watch queue must drop the watcher, never raise on the
    writing side (regression: sentinel put into a full queue raised Full)."""
    store = ObjectStore(watch_queue_size=2)
    w = store.watch(kinds=["JAXJob"])
    for i in range(6):
        j = tiny_job.model_copy(deep=True)
        j.metadata.name = f"tiny-{i}"
        store.create(j)  # must not raise
    events = w.drain()
    assert len(events) <= 2
    assert len(store.list(JAXJob)) == 6


def test_apply_create_or_update(store, tiny_job):
    store.apply(tiny_job)
    tiny_job.spec.replica_specs["worker"].template.config["steps"] = 9
    out = store.apply(tiny_job)
    assert out.spec.replica_specs["worker"].template.config["steps"] == 9
    assert out.metadata.generation == 2
