"""Spec validation + manifest round-trips (≈ the reference's webhook
validation tests and KFP golden-file compiler tests — SURVEY.md §4)."""

import pytest
from pydantic import ValidationError

from kubeflow_tpu.core.jobs import (
    ElasticPolicy, JAXJob, JAXJobSpec, ParallelismSpec, ReplicaSpec,
    RestartPolicy, TPUResourceSpec, WorkloadSpec, worker_name,
)
from kubeflow_tpu.core.manifest import dump_manifest, load_manifest, load_manifests
from kubeflow_tpu.core.object import ObjectMeta
from kubeflow_tpu.core.serving import InferenceService
from kubeflow_tpu.core.tuning import Experiment, ParameterSpec, ParameterType, FeasibleSpace
from kubeflow_tpu.core.workspace_specs import PodDefault, apply_pod_defaults


def job_spec(replicas=2, chips=1, **parallel):
    return JAXJobSpec(
        replica_specs={"worker": ReplicaSpec(
            replicas=replicas,
            template=WorkloadSpec(entrypoint="noop"),
            resources=TPUResourceSpec(tpu_chips=chips),
        )},
        parallelism=ParallelismSpec(**parallel) if parallel else ParallelismSpec(),
    )


def test_job_requires_worker_role():
    with pytest.raises(ValidationError):
        JAXJobSpec(replica_specs={"ps": ReplicaSpec(template=WorkloadSpec(entrypoint="x"))})


def test_parallelism_must_match_chip_count():
    job_spec(replicas=4, chips=2, fsdp=4, model=2)  # 8 chips = 4*2 ok
    with pytest.raises(ValidationError):
        job_spec(replicas=4, chips=2, fsdp=4, model=4)  # 16 != 8


def test_default_parallelism_of_one_is_always_valid():
    job_spec(replicas=8, chips=2)


def test_elastic_bounds_validated():
    with pytest.raises(ValidationError):
        ElasticPolicy(min_replicas=4, max_replicas=2)
    spec = job_spec(replicas=2)
    with pytest.raises(ValidationError):
        JAXJobSpec(
            replica_specs=spec.replica_specs,
            elastic_policy=ElasticPolicy(min_replicas=4, max_replicas=8),
        )


def test_elastic_autoscale_parallelism_validation():
    """The autoscaler scales the data/fsdp product and preserves the other
    axes; the preserved product must divide the job's chip total — checked
    at spec time instead of wedging a live gang."""
    auto = ElasticPolicy(min_replicas=1, max_replicas=8,
                         scale_on_headroom=True)
    assert auto.auto_scaling
    # pure DP (data == replicas) and default parallelism are fine
    spec = job_spec(replicas=2, data=2)
    JAXJobSpec(replica_specs=spec.replica_specs,
               parallelism=spec.parallelism, elastic_policy=auto)
    spec = job_spec(replicas=2)
    JAXJobSpec(replica_specs=spec.replica_specs, elastic_policy=auto)
    # TP/FSDP shardings now auto-scale too (the data/fsdp product scales,
    # model/expert/seq/pp keep their degrees)
    spec = job_spec(replicas=2, chips=2, data=2, model=2)
    JAXJobSpec(replica_specs=spec.replica_specs,
               parallelism=spec.parallelism, elastic_policy=auto)
    # the passive policy (no metric signals) stays unrestricted
    spec = job_spec(replicas=2, chips=2, data=2, model=2)
    JAXJobSpec(replica_specs=spec.replica_specs,
               parallelism=spec.parallelism,
               elastic_policy=ElasticPolicy(min_replicas=1, max_replicas=8))


def test_restart_policy_enum_from_manifest():
    doc = {
        "kind": "JAXJob",
        "metadata": {"name": "j1"},
        "spec": {
            "replica_specs": {"worker": {
                "replicas": 1,
                "restart_policy": "ExitCode",
                "template": {"entrypoint": "noop"},
            }},
        },
    }
    job = load_manifest(doc)
    assert job.spec.worker.restart_policy is RestartPolicy.EXIT_CODE


def test_manifest_yaml_roundtrip(tiny_job):
    text = dump_manifest(tiny_job)
    again = load_manifest(text)
    assert isinstance(again, JAXJob)
    assert again.spec == tiny_job.spec
    assert "apiVersion" in text and "training.tpu.kubeflow.dev/v1" in text


def test_multi_document_manifest():
    text = """
kind: JAXJob
metadata: {name: a}
spec:
  replica_specs:
    worker: {replicas: 1, template: {entrypoint: noop}}
---
kind: InferenceService
metadata: {name: b}
spec:
  predictor:
    model: {model_format: llm, model_name: m}
---
kind: Experiment
metadata: {name: c}
spec:
  parameters:
    - {name: lr, type: double, feasible_space: {min: 0.001, max: 0.1}}
  objective: {type: minimize, metric_name: loss}
  trial_template:
    manifest: {kind: JAXJob}
"""
    objs = load_manifests(text)
    assert [o.kind for o in objs] == ["JAXJob", "InferenceService", "Experiment"]
    assert isinstance(objs[1], InferenceService)
    assert isinstance(objs[2], Experiment)


def test_unknown_kind_rejected():
    with pytest.raises(KeyError):
        load_manifest({"kind": "FooBar", "metadata": {"name": "x"}, "spec": {}})


def test_extra_fields_rejected():
    with pytest.raises(ValidationError):
        JAXJob.from_manifest({
            "kind": "JAXJob",
            "metadata": {"name": "x"},
            "spec": {"replica_specs": {"worker": {"template": {"entrypoint": "n"}}},
                     "bogus_field": 1},
        })


def test_parameter_spec_validation():
    with pytest.raises(ValidationError):
        ParameterSpec(name="lr", type=ParameterType.DOUBLE,
                      feasible_space=FeasibleSpace(min=1.0, max=0.1))
    with pytest.raises(ValidationError):
        ParameterSpec(name="opt", type=ParameterType.CATEGORICAL,
                      feasible_space=FeasibleSpace())


def test_worker_naming():
    assert worker_name("llama", "worker", 3) == "llama-worker-3"


def test_pod_default_injection():
    pd = PodDefault(
        metadata=ObjectMeta(name="hf-cache"),
        spec={"selector": {"team": "nlp"}, "env": {"HF_HOME": "/cache"}},
    )
    env = apply_pod_defaults({"team": "nlp"}, {"A": "1"}, [pd])
    assert env == {"HF_HOME": "/cache", "A": "1"}
    env = apply_pod_defaults({"team": "vision"}, {"A": "1"}, [pd])
    assert env == {"A": "1"}
    # explicit env wins over injected
    env = apply_pod_defaults({"team": "nlp"}, {"HF_HOME": "/mine"}, [pd])
    assert env == {"HF_HOME": "/mine"}


def test_condition_transitions(tiny_job):
    st = tiny_job.status
    st.set_condition("Created")
    st.set_condition("Running")
    assert st.phase == "Running"
    st.set_condition("Running", status=False, reason="WorkerDied")
    st.set_condition("Restarting")
    assert st.phase == "Restarting"
    st.set_condition("Restarting", status=False)
    st.set_condition("Running")
    st.set_condition("Succeeded")
    assert st.phase == "Succeeded"
