"""Platform tracing (obs/trace.py): tracer mechanics, Chrome export, the
propagation contract (one trace id router → server → engine with nested
queued/prefill/decode spans), failure-status closure on cancelled/expired
requests with a quiescent ring buffer, the slow-request log, and the
controller/pipeline span hooks."""

import json
import threading
import time
import urllib.request

import pytest
import jax

from kubeflow_tpu.obs.trace import (
    Tracer, format_trace_tree, get_tracer, parse_trace_header,
)

TRACER = get_tracer()


@pytest.fixture(autouse=True)
def _fresh_tracer():
    TRACER.reset()
    yield
    TRACER.reset()


# -- tracer mechanics ----------------------------------------------------------

def test_contextvar_nesting_and_status():
    t = Tracer()
    with t.span("root", path="/x") as root:
        with t.span("child") as child:
            assert child.parent_id == root.span_id
            assert child.trace_id == root.trace_id
        assert t.current() is root
    assert t.current() is None
    tr = t.traces()[0]
    assert tr["root"]["name"] == "root"
    assert {s["name"] for s in tr["spans"]} == {"root", "child"}
    assert t.open_spans() == 0


def test_exception_marks_span_error():
    t = Tracer()
    with pytest.raises(RuntimeError):
        with t.span("boom"):
            raise RuntimeError("nope")
    tr = t.traces()[0]
    assert tr["root"]["status"] == "error"
    assert "RuntimeError" in tr["root"]["attrs"]["error"]
    assert t.open_spans() == 0


def test_cross_thread_parenting():
    t = Tracer()
    with t.span("root") as root:
        ctx = root.context
        done = threading.Event()

        def worker():
            sp = t.start_span("engine.work", parent=ctx)
            sp.end()
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(5)
    spans = t.traces()[0]["spans"]
    assert {s["trace_id"] for s in spans} == {root.trace_id}


def test_header_roundtrip_and_garbage():
    t = Tracer()
    with t.span("root") as root:
        hdr = t.inject(root)
    ctx = parse_trace_header(hdr)
    assert ctx.trace_id == root.trace_id
    assert ctx.span_id == root.span_id
    assert parse_trace_header(None) is None
    assert parse_trace_header("not hex at all!") is None
    assert parse_trace_header("deadbeef") is None   # no separator


def test_disabled_tracer_is_noop():
    t = Tracer()
    t.enabled = False
    with t.span("root") as sp:
        sp.set_attrs(x=1)
        sp.add_event("e")
    assert t.traces() == []
    assert t.open_spans() == 0


def test_ring_buffer_bounded():
    t = Tracer(max_traces=4)
    for i in range(10):
        with t.span(f"r{i}"):
            pass
    assert len(t.traces()) == 4


def test_chrome_export_valid():
    t = Tracer()
    with t.span("root"):
        with t.span("child"):
            pass
    doc = json.loads(json.dumps(t.export_chrome()))
    assert doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], float) and isinstance(ev["dur"], float)
        assert isinstance(ev["tid"], int)


def test_slowest_filter():
    t = Tracer()
    with t.span("fast"):
        pass
    with t.span("slow"):
        time.sleep(0.05)
    slowest = t.traces(slowest=1)
    assert len(slowest) == 1
    assert slowest[0]["root"]["name"] == "slow"


def test_slow_request_log(caplog):
    t = Tracer(slow_threshold_s=0.01)
    with caplog.at_level("WARNING", logger="kubeflow_tpu.obs.slow"):
        with t.span("root"):
            with t.span("inner"):
                time.sleep(0.03)
    assert any("slow request" in r.message for r in caplog.records)
    assert any("inner" in r.getMessage() for r in caplog.records)


def test_format_tree_handles_orphans():
    out = format_trace_tree([
        {"span_id": "b", "parent_id": "missing", "name": "orphan",
         "start": 1.0, "duration_ms": 2.0, "status": "ok", "attrs": {}},
    ])
    assert "orphan" in out


# -- engine lifecycle spans ----------------------------------------------------

@pytest.fixture(scope="module")
def tiny_engine():
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.serve.engine import LLMEngine

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    return LLMEngine(
        cfg, BatchingSpec(max_batch_size=2, max_seq_len=64,
                          prefill_buckets=[32]),
        params=params)


def test_engine_spans_one_trace(tiny_engine):
    from kubeflow_tpu.serve.engine import SamplingParams

    with TRACER.span("server.request") as root:
        req = tiny_engine.submit([1, 2, 3], SamplingParams(max_new_tokens=3),
                                 trace_parent=root)
        while not req.done.is_set():
            tiny_engine.step()
    tr = TRACER.trace(root.trace_id)
    names = [s["name"] for s in tr["spans"]]
    assert "engine.queued" in names
    assert "engine.prefill" in names
    assert "engine.decode" in names
    decode = next(s for s in tr["spans"] if s["name"] == "engine.decode")
    assert decode["status"] == "ok"
    assert decode["attrs"]["finish_reason"] in ("length", "stop")
    assert any(e["name"] == "decode_round" for e in decode["events"])
    assert TRACER.open_spans() == 0


def test_cancelled_request_closes_span_cancelled(tiny_engine):
    from kubeflow_tpu.serve.engine import SamplingParams

    with TRACER.span("server.request") as root:
        req = tiny_engine.submit([5, 6, 7],
                                 SamplingParams(max_new_tokens=50),
                                 trace_parent=root)
        req.cancel()
        for _ in range(50):
            tiny_engine.step()
            if req.done.is_set():
                break
    assert req.finish_reason == "cancelled"
    tr = TRACER.trace(root.trace_id)
    engine_spans = [s for s in tr["spans"] if s["name"].startswith("engine.")]
    assert engine_spans, "cancelled request left no engine span"
    assert any(s["status"] == "cancelled" for s in engine_spans)
    # the quiescence invariant: nothing left open after the reap
    assert TRACER.open_spans() == 0


def test_expired_request_closes_span_error(tiny_engine):
    from kubeflow_tpu.serve.engine import SamplingParams

    with TRACER.span("server.request") as root:
        req = tiny_engine.submit([9, 10],
                                 SamplingParams(max_new_tokens=50),
                                 trace_parent=root,
                                 deadline=time.monotonic() - 1.0)
        for _ in range(50):
            tiny_engine.step()
            if req.done.is_set():
                break
    assert req.finish_reason == "deadline"
    tr = TRACER.trace(root.trace_id)
    statuses = {s["status"] for s in tr["spans"]
                if s["name"].startswith("engine.")}
    assert "error" in statuses
    assert TRACER.open_spans() == 0


def test_untraced_requests_pay_nothing(tiny_engine):
    from kubeflow_tpu.serve.engine import SamplingParams

    req = tiny_engine.submit([1, 2], SamplingParams(max_new_tokens=2))
    while not req.done.is_set():
        tiny_engine.step()
    assert req.span is None
    assert TRACER.open_spans() == 0
    assert TRACER.traces() == []


# -- HTTP propagation e2e ------------------------------------------------------

@pytest.fixture(scope="module")
def routed_stack(tiny_engine):
    from kubeflow_tpu.serve.router import Router
    from kubeflow_tpu.serve.server import ModelServer

    server = ModelServer("trace-demo", tiny_engine, port=0)
    server.start()
    router = Router(queue_timeout=5.0, upstream_timeout=60.0)
    router.set_backends({"latest": [server.url]})
    router.start()
    yield router, server
    router.stop()
    server.httpd.shutdown()
    server.httpd.server_close()


def _post(url: str, body: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _wait_for(pred, timeout: float = 10.0) -> bool:
    """The HTTP client can observe the response bytes a beat before the
    router handler's span context manager exits — poll instead of racing
    the handler thread."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def _router_rooted_traces():
    return [t for t in TRACER.traces()
            if t["root"] and t["root"]["name"] == "router.request"]


def test_one_trace_id_router_to_engine(routed_stack):
    router, server = routed_stack
    out = _post(router.url + "/v1/completions",
                {"prompt": "hi", "max_tokens": 3})
    assert out["usage"]["completion_tokens"] >= 1
    # one trace, one id, ≥3 nested spans under the router root
    assert _wait_for(lambda: _router_rooted_traces()), \
        "router did not root a trace"
    tr = _router_rooted_traces()[0]
    ids = {s["trace_id"] for s in tr["spans"]}
    assert len(ids) == 1
    names = {s["name"] for s in tr["spans"]}
    assert {"router.request", "server.request", "engine.queued",
            "engine.prefill", "engine.decode"} <= names
    # nesting: server.request under router.request, engine spans under
    # server.request
    by_id = {s["span_id"]: s for s in tr["spans"]}
    srv = next(s for s in tr["spans"] if s["name"] == "server.request")
    assert by_id[srv["parent_id"]]["name"] == "router.request"
    for name in ("engine.queued", "engine.prefill", "engine.decode"):
        sp = next(s for s in tr["spans"] if s["name"] == name)
        assert by_id[sp["parent_id"]]["name"] == "server.request"
    assert _wait_for(lambda: TRACER.open_spans() == 0)


def test_client_supplied_header_joins(routed_stack):
    router, _ = routed_stack
    body = json.dumps({"prompt": "x", "max_tokens": 2}).encode()
    req = urllib.request.Request(
        router.url + "/v1/completions", data=body,
        headers={"Content-Type": "application/json",
                 "X-Kftpu-Trace": "ab12cd34" * 4 + "-" + "12ef" * 4})
    with urllib.request.urlopen(req, timeout=120) as r:
        json.loads(r.read())
    tr = TRACER.trace("ab12cd34" * 4)
    assert tr is not None, "client trace id was not joined"
    assert any(s["name"] == "engine.decode" for s in tr["spans"])


def test_debug_traces_endpoint(routed_stack):
    router, server = routed_stack
    _post(router.url + "/v1/completions", {"prompt": "q", "max_tokens": 2})
    assert _wait_for(lambda: _router_rooted_traces())
    with urllib.request.urlopen(server.url + "/debug/traces?slowest=1",
                                timeout=10) as r:
        doc = json.loads(r.read())
    assert len(doc["traces"]) == 1
    assert doc["traces"][0]["root"] is not None
    with urllib.request.urlopen(
            router.url + "/-/router/debug/traces", timeout=10) as r:
        doc = json.loads(r.read())
    assert doc["traces"]
    with urllib.request.urlopen(server.url + "/debug/traces?chrome=1",
                                timeout=10) as r:
        chrome = json.loads(r.read())
    assert chrome["traceEvents"]


# -- controller + pipeline hooks -----------------------------------------------

def test_controller_reconcile_span(store):
    from kubeflow_tpu.core.jobs import JAXJob
    from kubeflow_tpu.operator.controller import Controller

    class Recon:
        kinds = [JAXJob.KIND]

        def key_for(self, ev):
            return ev.object.metadata.key

        def reconcile(self, key):
            sp = TRACER.current()
            assert sp is not None and sp.name == "reconcile"
            return None

    ctrl = Controller(store, Recon(), name="test-ctrl")
    from kubeflow_tpu.core.object import ObjectMeta
    from kubeflow_tpu.core.jobs import (
        JAXJobSpec, ReplicaSpec, TPUResourceSpec, WorkloadSpec,
    )

    store.apply(JAXJob(
        metadata=ObjectMeta(name="t", namespace="default"),
        spec=JAXJobSpec(replica_specs={"worker": ReplicaSpec(
            replicas=1,
            template=WorkloadSpec(entrypoint="noop", config={}),
            resources=TPUResourceSpec(tpu_chips=1))})))
    assert ctrl.step() >= 1
    spans = [t for t in TRACER.traces()
             if t["root"] and t["root"]["name"] == "reconcile"]
    assert spans
    assert spans[0]["root"]["attrs"]["controller"] == "test-ctrl"
    assert TRACER.open_spans() == 0


def test_crashing_reconcile_span_closes_error(store):
    from kubeflow_tpu.core.jobs import JAXJob
    from kubeflow_tpu.operator.controller import Controller

    class Bad:
        kinds = [JAXJob.KIND]

        def key_for(self, ev):
            return ev.object.metadata.key

        def reconcile(self, key):
            raise RuntimeError("kaboom")

    ctrl = Controller(store, Bad(), name="bad-ctrl")
    from kubeflow_tpu.core.object import ObjectMeta
    from kubeflow_tpu.core.jobs import (
        JAXJobSpec, ReplicaSpec, TPUResourceSpec, WorkloadSpec,
    )

    store.apply(JAXJob(
        metadata=ObjectMeta(name="b", namespace="default"),
        spec=JAXJobSpec(replica_specs={"worker": ReplicaSpec(
            replicas=1,
            template=WorkloadSpec(entrypoint="noop", config={}),
            resources=TPUResourceSpec(tpu_chips=1))})))
    ctrl.step()
    spans = [t for t in TRACER.traces()
             if t["root"] and t["root"]["name"] == "reconcile"]
    assert spans and spans[0]["root"]["status"] == "error"
    assert TRACER.open_spans() == 0


def test_pipeline_run_and_task_spans(tmp_path):
    from kubeflow_tpu.pipelines import dsl
    from kubeflow_tpu.pipelines.artifacts import ArtifactStore
    from kubeflow_tpu.pipelines.compiler import compile_pipeline
    from kubeflow_tpu.pipelines.executor import PipelineExecutor
    from kubeflow_tpu.pipelines.metadata import MetadataStore

    @dsl.component
    def add_one(x: int) -> int:
        return x + 1

    @dsl.component
    def add_two(x: int) -> int:
        return x + 2

    @dsl.pipeline
    def pipe(x: int = 1):
        a = add_one(x=x)
        add_two(x=a.output)

    ir = compile_pipeline(pipe)
    ex = PipelineExecutor(ArtifactStore(str(tmp_path / "cas")),
                          MetadataStore(str(tmp_path / "md.db")))
    result = ex.run(ir, run_name="t1")
    assert result.phase.value == "Succeeded"
    runs = [t for t in TRACER.traces()
            if t["root"] and t["root"]["name"] == "pipeline.run"]
    assert runs
    tr = runs[0]
    tasks = [s for s in tr["spans"] if s["name"] == "pipeline.task"]
    assert len(tasks) == 2
    assert all(s["parent_id"] == tr["root"]["span_id"] for s in tasks)
    assert TRACER.open_spans() == 0
