"""Compiler tests — the KFP compiler golden-file pattern ((U) kubeflow/
pipelines sdk/python/kfp/compiler/compiler_test.py; SURVEY.md §4.4): compile
the DSL, diff against a checked-in IR YAML snapshot; plus DAG validation."""

import os
from typing import NamedTuple

import pytest

from kubeflow_tpu.pipelines import dsl
from kubeflow_tpu.pipelines.compiler import (
    compile_pipeline, from_yaml, to_yaml, topo_order,
)

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "demo_pipeline.yaml")


@dsl.component
def ingest(source: str) -> list:
    return [source]


@dsl.component
def transform(data: list, factor: int = 2) -> NamedTuple(
        "Out", [("rows", list), ("count", int)]):
    from collections import namedtuple
    return namedtuple("Out", ["rows", "count"])(data * factor, len(data) * factor)


@dsl.component(cache=False, resources={"tpu_chips": 1})
def train(rows: list) -> float:
    return float(len(rows))


@dsl.component
def notify(score: float) -> str:
    return f"score={score}"


@dsl.pipeline(name="demo-pipeline", description="golden-file demo")
def demo(source: str = "db", factor: int = 2):
    i = ingest(source=source)
    t = transform(data=i.output, factor=factor)
    tr = train(rows=t.outputs["rows"])
    with dsl.Condition(tr.output >= 1.0):
        notify(score=tr.output)


class TestCompile:
    def test_golden_file(self):
        got = to_yaml(compile_pipeline(demo))
        if not os.path.exists(GOLDEN):  # bootstrap the snapshot
            os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
            with open(GOLDEN, "w") as f:
                f.write(got)
        with open(GOLDEN) as f:
            want = f.read()
        assert got == want, (
            "compiled IR drifted from the golden snapshot; if intentional, "
            f"delete {GOLDEN} and rerun")

    def test_yaml_round_trip(self):
        ir = compile_pipeline(demo)
        assert from_yaml(to_yaml(ir)) == ir

    def test_structure(self):
        ir = compile_pipeline(demo)
        assert set(ir.tasks) == {"ingest", "transform", "train", "notify"}
        assert ir.tasks["transform"].depends_on == ["ingest"]
        assert ir.tasks["notify"].condition == {"all": [{
            "op": ">=", "lhs": {"task_output": "train.output"},
            "rhs": {"constant": 1.0}}]}
        assert not ir.components["train"].cache_enabled
        assert ir.components["train"].resources == {"tpu_chips": 1}
        assert ir.parameters == {"source": "db", "factor": 2}
        assert topo_order(ir) == ["ingest", "transform", "train", "notify"]

    def test_duplicate_invocations_get_unique_names(self):
        @dsl.pipeline
        def twice():
            ingest(source="a")
            ingest(source="b")

        ir = compile_pipeline(twice)
        assert set(ir.tasks) == {"ingest", "ingest-2"}


class TestValidation:
    def test_unknown_kwarg(self):
        @dsl.pipeline
        def bad():
            ingest(sauce="a")

        with pytest.raises(TypeError, match="unknown inputs"):
            compile_pipeline(bad)

    def test_missing_input(self):
        @dsl.pipeline
        def bad():
            ingest()

        with pytest.raises(TypeError, match="missing inputs"):
            compile_pipeline(bad)

    def test_positional_args_rejected(self):
        @dsl.pipeline
        def bad():
            ingest("a")

        with pytest.raises(TypeError, match="keyword"):
            compile_pipeline(bad)

    def test_condition_outside_pipeline(self):
        with pytest.raises(RuntimeError, match="outside a @pipeline"):
            with dsl.Condition(dsl.PipelineParam("x") > 1):
                pass

    def test_bool_of_reference_is_an_error(self):
        @dsl.pipeline
        def bad(x: int = 1):
            if dsl.PipelineParam("x") > 1:  # plain if on a placeholder
                ingest(source="a")

        with pytest.raises(RuntimeError, match="placeholder"):
            compile_pipeline(bad)

    def test_component_plain_call_outside_pipeline(self):
        # Outside a trace a component is just the function (unit-testable).
        assert ingest(source="s") == ["s"]
        assert train(rows=[1, 2]) == 2.0


@dsl.component
def shard_work(group: str, item: int) -> int:
    return item


@dsl.component
def collect(items: list) -> int:
    return len(items)


NESTED_GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                             "nested_loops_pipeline.yaml")


@dsl.pipeline(name="nested-loops", description="nested ParallelFor demo")
def nested_loops():
    groups = [{"name": "a", "xs": [1, 2]}, {"name": "b", "xs": [3]}]
    with dsl.ParallelFor(groups) as g:
        with dsl.ParallelFor(g["xs"]) as x:
            w = shard_work(group=g["name"], item=x)
    collect(items=w.output)


class TestNestedLoopIR:
    def test_nested_golden_file(self):
        """Nested ParallelFor compiles to stacked iterate_over levels
        (outermost→innermost), the inner items referencing the outer
        loop_item — pinned as a golden snapshot (the KFP compiler-test
        pattern)."""
        got = to_yaml(compile_pipeline(nested_loops))
        if not os.path.exists(NESTED_GOLDEN):  # bootstrap the snapshot
            os.makedirs(os.path.dirname(NESTED_GOLDEN), exist_ok=True)
            with open(NESTED_GOLDEN, "w") as f:
                f.write(got)
        with open(NESTED_GOLDEN) as f:
            want = f.read()
        assert got == want, (
            "compiled IR drifted from the golden snapshot; if intentional, "
            f"delete {NESTED_GOLDEN} and rerun")

    def test_nested_ir_structure(self):
        ir = compile_pipeline(nested_loops)
        t = ir.tasks["shard_work"]
        assert len(t.iterate_over) == 2
        outer, inner = t.iterate_over
        assert "constant" in outer["items"]
        assert inner["items"]["loop_item"] == outer["loop_id"]
        assert inner["items"]["subpath"] == "xs"
        # Single-level IR stays a one-element list (dict form coerces too).
        assert from_yaml(to_yaml(ir)) == ir
