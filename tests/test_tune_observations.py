"""Durable observation-log history (katib db-manager analog, (U) katib
cmd/db-manager + pkg/db; SURVEY.md §2.4#33): per-step logs in the native
metadata store, resume-safe upserts, cross-experiment queries."""

import pytest

from kubeflow_tpu.pipelines.metadata import (
    EXEC_COMPLETE, EXEC_FAILED, MetadataStore,
)
from kubeflow_tpu.tune.observations import ObservationLog


@pytest.fixture(params=["python", "native"])
def log(request, tmp_path):
    try:
        store = MetadataStore(str(tmp_path / "obs.db"),
                              backend=request.param)
    except RuntimeError:
        pytest.skip("native backend unavailable")
    yield ObservationLog(store)
    store.close()


def test_report_and_get_log(log):
    log.report("default/exp1", "t1", "loss", [(0, 2.0), (10, 1.5), (20, 1.1)],
               parameters={"lr": 0.01})
    log.report("default/exp1", "t1", "accuracy", [(10, 0.4)])
    got = log.get_log("t1")
    assert got["loss"] == [(0, 2.0), (10, 1.5), (20, 1.1)]
    assert got["accuracy"] == [(10, 0.4)]
    assert log.get_log("t1", "loss") == {"loss": [(0, 2.0), (10, 1.5),
                                                 (20, 1.1)]}


def test_report_is_resume_safe_upsert(log):
    log.report("default/exp1", "t1", "loss", [(0, 2.0), (10, 1.5)])
    # Re-reporting the full history (restart) must not duplicate points and
    # must take the newest value for a step.
    log.report("default/exp1", "t1", "loss", [(0, 2.0), (10, 1.4), (20, 1.0)])
    assert log.get_log("t1")["loss"] == [(0, 2.0), (10, 1.4), (20, 1.0)]


def test_legacy_property_rows_merge_with_table(log):
    """A trial spanning the migration: points written as obs:* properties
    (rounds 1-3) and points in the observations table must read as ONE
    series, table winning on a shared step."""
    from kubeflow_tpu.pipelines.metadata import EXECUTION

    eid = log.trial_execution("default/exp1", "old")
    log.store._set_props(EXECUTION, eid, {
        "obs:loss:00000000": 3.0,
        "obs:loss:00000005": 2.0,          # superseded by the table below
        "obs:val:loss:00000002": 9.0,      # metric name containing ':'
    })
    log.report("default/exp1", "old", "loss", [(5, 1.5), (10, 1.0)])
    got = log.get_log("old")
    assert got["loss"] == [(0, 3.0), (5, 1.5), (10, 1.0)]
    assert got["val:loss"] == [(2, 9.0)]
    assert log.best("default/exp1", "loss") == ("old", 1.0)


def test_cross_experiment_queries(log):
    log.report("default/sweep-a", "a-0", "loss", [(0, 3.0), (5, 1.0)])
    log.report("default/sweep-a", "a-1", "loss", [(0, 3.0), (5, 2.0)])
    log.report("default/sweep-b", "b-0", "loss", [(0, 0.5)])
    assert sorted(log.experiments()) == ["default/sweep-a", "default/sweep-b"]
    trials = log.trials("default/sweep-a")
    assert sorted(t["trial"] for t in trials) == ["a-0", "a-1"]
    assert log.best("default/sweep-a", "loss") == ("a-0", 1.0)
    assert log.best("default/sweep-b", "loss") == ("b-0", 0.5)


def test_trial_params_and_state(log):
    log.report("default/e", "t9", "loss", [(0, 1.0)],
               parameters={"lr": 0.1, "opt": "adam"})
    log.finish_trial("t9", succeeded=True)
    (t,) = log.trials("default/e")
    assert t["parameters"] == {"lr": 0.1, "opt": "adam"}
    assert t["state"] == EXEC_COMPLETE
    log.finish_trial("t9", succeeded=False)
    (t,) = log.trials("default/e")
    assert t["state"] == EXEC_FAILED


def test_survives_reopen(tmp_path):
    path = str(tmp_path / "obs.db")
    store = MetadataStore(path, backend="python")
    ObservationLog(store).report("default/e", "t1", "loss", [(0, 1.0)])
    store.close()
    store = MetadataStore(path, backend="python")
    log = ObservationLog(store)
    assert log.get_log("t1")["loss"] == [(0, 1.0)]
    assert log.experiments() == ["default/e"]
    store.close()


def test_trial_controller_writes_observations(tmp_path):
    """The tune flow must land observations in the durable store — queryable
    after the Trial objects are gone."""
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.tune.client import build_experiment, parameter

    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path), launch_processes=False,
        metrics_sync_interval=None))
    exp = build_experiment(
        "sweep", entrypoint="noop",
        parameters=[parameter("lr", min=0.001, max=0.1)],
        objective_metric="loss", max_trial_count=2, parallel_trial_count=2,
        metric_source="push")
    plane.submit(exp)
    plane.step()
    # Fabricate job metrics (envtest style: no processes run).
    from kubeflow_tpu.core.jobs import JAXJob

    for job in plane.store.list(JAXJob):
        job.status.metrics.step = 3
        job.status.metrics.loss = 0.5
        job.status.set_condition("Running")
        plane.store.update_status(job)
    plane.step()
    trials = plane.observations.trials("default/sweep")
    assert len(trials) >= 1
    name = trials[0]["trial"]
    assert plane.observations.get_log(name)["loss"]
    assert "lr" in trials[0]["parameters"]
    plane.stop()


def test_grpc_front_round_trip(tmp_path):
    """The db-manager gRPC surface: report/query through the wire equals
    the in-process log."""
    from kubeflow_tpu.tune.observation_service import (
        ObservationGRPCServer, RemoteObservationLog,
    )

    store = MetadataStore(str(tmp_path / "obs.db"))
    log = ObservationLog(store)
    srv = ObservationGRPCServer(log)
    srv.start()
    try:
        remote = RemoteObservationLog(srv.target)
        remote.report("default/e1", "t1", "loss", [(0, 2.0), (5, 1.0)],
                      parameters={"lr": 0.1})
        assert remote.get_log("t1")["loss"] == [(0, 2.0), (5, 1.0)]
        assert remote.experiments() == ["default/e1"]
        (t,) = remote.trials("default/e1")
        assert t["trial"] == "t1" and t["parameters"] == {"lr": 0.1}
        assert remote.best("default/e1", "loss") == ("t1", 1.0)
        remote.finish_trial("t1")
        remote.close()
        # The same data is visible to the in-process log object.
        assert log.get_log("t1")["loss"] == [(0, 2.0), (5, 1.0)]
    finally:
        srv.stop()
        store.close()


def test_worker_reports_directly_over_grpc(tmp_path):
    """A REAL worker process writes observations straight to the store's
    gRPC front (no controller relay): the runtime injects KFTPU_OBS_TARGET
    and the points land in the durable log."""
    from kubeflow_tpu.core.jobs import (
        JAXJob, JAXJobSpec, ReplicaSpec, TPUResourceSpec, WorkloadSpec,
    )
    from kubeflow_tpu.core.object import ObjectMeta
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

    cp = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu"))
    cp.start()
    try:
        job = cp.submit(JAXJob(
            metadata=ObjectMeta(name="obsjob"),
            spec=JAXJobSpec(replica_specs={"worker": ReplicaSpec(
                replicas=1,
                template=WorkloadSpec(
                    entrypoint="tests.obs_worker:report_obs"),
                resources=TPUResourceSpec(tpu_chips=1))})))
        cp.wait_for(job, "Succeeded", timeout=120)
        got = cp.observations.get_log("grpc-trial")
        assert got["loss"] == [(0, 3.0), (1, 2.0), (2, 1.0)]
        (t,) = cp.observations.trials("default/grpc-exp")
        assert t["parameters"] == {"lr": 0.5}
    finally:
        cp.stop()
