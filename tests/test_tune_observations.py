"""Durable observation-log history (katib db-manager analog, (U) katib
cmd/db-manager + pkg/db; SURVEY.md §2.4#33): per-step logs in the native
metadata store, resume-safe upserts, cross-experiment queries."""

import pytest

from kubeflow_tpu.pipelines.metadata import (
    EXEC_COMPLETE, EXEC_FAILED, MetadataStore,
)
from kubeflow_tpu.tune.observations import ObservationLog


@pytest.fixture(params=["python", "native"])
def log(request, tmp_path):
    try:
        store = MetadataStore(str(tmp_path / "obs.db"),
                              backend=request.param)
    except RuntimeError:
        pytest.skip("native backend unavailable")
    yield ObservationLog(store)
    store.close()


def test_report_and_get_log(log):
    log.report("default/exp1", "t1", "loss", [(0, 2.0), (10, 1.5), (20, 1.1)],
               parameters={"lr": 0.01})
    log.report("default/exp1", "t1", "accuracy", [(10, 0.4)])
    got = log.get_log("t1")
    assert got["loss"] == [(0, 2.0), (10, 1.5), (20, 1.1)]
    assert got["accuracy"] == [(10, 0.4)]
    assert log.get_log("t1", "loss") == {"loss": [(0, 2.0), (10, 1.5),
                                                 (20, 1.1)]}


def test_report_is_resume_safe_upsert(log):
    log.report("default/exp1", "t1", "loss", [(0, 2.0), (10, 1.5)])
    # Re-reporting the full history (restart) must not duplicate points and
    # must take the newest value for a step.
    log.report("default/exp1", "t1", "loss", [(0, 2.0), (10, 1.4), (20, 1.0)])
    assert log.get_log("t1")["loss"] == [(0, 2.0), (10, 1.4), (20, 1.0)]


def test_cross_experiment_queries(log):
    log.report("default/sweep-a", "a-0", "loss", [(0, 3.0), (5, 1.0)])
    log.report("default/sweep-a", "a-1", "loss", [(0, 3.0), (5, 2.0)])
    log.report("default/sweep-b", "b-0", "loss", [(0, 0.5)])
    assert sorted(log.experiments()) == ["default/sweep-a", "default/sweep-b"]
    trials = log.trials("default/sweep-a")
    assert sorted(t["trial"] for t in trials) == ["a-0", "a-1"]
    assert log.best("default/sweep-a", "loss") == ("a-0", 1.0)
    assert log.best("default/sweep-b", "loss") == ("b-0", 0.5)


def test_trial_params_and_state(log):
    log.report("default/e", "t9", "loss", [(0, 1.0)],
               parameters={"lr": 0.1, "opt": "adam"})
    log.finish_trial("t9", succeeded=True)
    (t,) = log.trials("default/e")
    assert t["parameters"] == {"lr": 0.1, "opt": "adam"}
    assert t["state"] == EXEC_COMPLETE
    log.finish_trial("t9", succeeded=False)
    (t,) = log.trials("default/e")
    assert t["state"] == EXEC_FAILED


def test_survives_reopen(tmp_path):
    path = str(tmp_path / "obs.db")
    store = MetadataStore(path, backend="python")
    ObservationLog(store).report("default/e", "t1", "loss", [(0, 1.0)])
    store.close()
    store = MetadataStore(path, backend="python")
    log = ObservationLog(store)
    assert log.get_log("t1")["loss"] == [(0, 1.0)]
    assert log.experiments() == ["default/e"]
    store.close()


def test_trial_controller_writes_observations(tmp_path):
    """The tune flow must land observations in the durable store — queryable
    after the Trial objects are gone."""
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.tune.client import build_experiment, parameter

    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path), launch_processes=False,
        metrics_sync_interval=None))
    exp = build_experiment(
        "sweep", entrypoint="noop",
        parameters=[parameter("lr", min=0.001, max=0.1)],
        objective_metric="loss", max_trial_count=2, parallel_trial_count=2,
        metric_source="push")
    plane.submit(exp)
    plane.step()
    # Fabricate job metrics (envtest style: no processes run).
    from kubeflow_tpu.core.jobs import JAXJob

    for job in plane.store.list(JAXJob):
        job.status.metrics.step = 3
        job.status.metrics.loss = 0.5
        job.status.set_condition("Running")
        plane.store.update_status(job)
    plane.step()
    trials = plane.observations.trials("default/sweep")
    assert len(trials) >= 1
    name = trials[0]["trial"]
    assert plane.observations.get_log(name)["loss"]
    assert "lr" in trials[0]["parameters"]
    plane.stop()
