"""Trace-driven serving loadgen (ISSUE 11): schedule determinism,
arrival-process statistics, shared-prefix generation, the shared
quantile helpers at their exact boundaries, the threshold gate's
regression logic, and an end-to-end scenario run against a real engine
with the full attribution join (client percentiles + /metrics scrape +
per-phase span breakdowns) and a quiescent trace ring."""

import math
import time

import numpy as np
import pytest
import jax

from kubeflow_tpu.loadgen import (
    ATTRIBUTION_SERIES, Arrival, EngineTarget, LengthDist, Scenario,
    arrival_times, build_report, build_schedule, compare_matrix,
    compare_scenario, measured_prefix_overlap, noise_band_pct,
    report_registry, run_scenario, spread_pct, standard_matrix,
)
from kubeflow_tpu.obs import stats
from kubeflow_tpu.obs.trace import Tracer, get_tracer

TRACER = get_tracer()


@pytest.fixture(autouse=True)
def _fresh_tracer():
    TRACER.reset()
    yield
    TRACER.reset()


# -- stats: the one quantile implementation ------------------------------------

class TestStats:
    def test_exact_boundaries(self):
        xs = [5.0, 1.0, 3.0, 2.0, 4.0]
        assert stats.quantile(xs, 0.0) == 1.0       # min
        assert stats.quantile(xs, 1.0) == 5.0       # max
        assert stats.quantile(xs, 0.5) == 3.0       # odd-length median

    def test_interpolation_matches_numpy(self):
        rng = np.random.default_rng(7)
        xs = rng.exponential(1.0, size=257).tolist()
        for q in (0.0, 0.1, 0.5, 0.9, 0.95, 0.99, 1.0):
            assert stats.quantile(xs, q) == pytest.approx(
                float(np.percentile(np.asarray(xs), q * 100)), rel=1e-12)

    def test_single_element_and_pair(self):
        assert stats.quantile([2.5], 0.95) == 2.5
        # even-length median interpolates halfway
        assert stats.quantile([1.0, 2.0], 0.5) == 1.5

    def test_empty_and_bad_q_raise(self):
        with pytest.raises(ValueError):
            stats.quantile([], 0.5)
        with pytest.raises(ValueError):
            stats.quantile([1.0], 1.5)

    def test_quantiles_ms_keys_and_units(self):
        out = stats.quantiles_ms([0.010, 0.020, 0.030])
        assert set(out) == {"p50", "p95", "p99"}
        assert out["p50"] == 20.0
        assert stats.quantiles_ms([]) == {}

    def test_engine_metrics_uses_shared_quantile(self):
        # The p95 the engine snapshot reports must be the SAME statistic
        # as the client-side report (numpy linear interpolation).
        from kubeflow_tpu.serve.engine import EngineMetrics

        m = EngineMetrics()
        for v in (0.1, 0.2, 0.3, 0.4):
            m.observe_queue_delay(v)
        snap = m.snapshot()
        assert snap["queue_delay_p95_ms"] == pytest.approx(
            stats.quantile([0.1, 0.2, 0.3, 0.4], 0.95) * 1e3)


# -- schedule determinism ------------------------------------------------------

class TestScheduleDeterminism:
    SC = Scenario(name="det", num_requests=40,
                  arrival=Arrival(process="poisson", rate_rps=20.0),
                  prompt_len=LengthDist(kind="lognormal", mu=3.0,
                                        sigma=0.5, low=4, high=64),
                  output_len=LengthDist(kind="uniform", low=2, high=9),
                  qos_mix=(("interactive", 1.0), ("batch", 3.0)),
                  prefix_overlap=0.5, seed=42)

    def test_same_seed_identical_schedule(self):
        a = build_schedule(self.SC, vocab_size=256, max_prompt_len=100)
        b = build_schedule(self.SC, vocab_size=256, max_prompt_len=100)
        assert [(r.t, r.prompt_tokens, r.max_new_tokens, r.qos)
                for r in a] == \
               [(r.t, r.prompt_tokens, r.max_new_tokens, r.qos)
                for r in b]

    def test_different_seed_differs(self):
        import dataclasses

        a = build_schedule(self.SC, vocab_size=256, max_prompt_len=100)
        c = build_schedule(dataclasses.replace(self.SC, seed=43),
                           vocab_size=256, max_prompt_len=100)
        assert [r.prompt_tokens for r in a] != [r.prompt_tokens for r in c]

    def test_qos_mix_fractions(self):
        sched = build_schedule(
            Scenario(name="mix", num_requests=800,
                     qos_mix=(("interactive", 1.0), ("batch", 3.0)),
                     seed=3),
            vocab_size=256, max_prompt_len=64)
        frac = sum(1 for r in sched if r.qos == "batch") / len(sched)
        assert abs(frac - 0.75) < 0.05

    def test_unknown_qos_class_rejected(self):
        sc = Scenario(name="bad", qos_mix=(("gold", 1.0),))
        with pytest.raises(ValueError, match="gold"):
            build_schedule(sc, vocab_size=256, max_prompt_len=64)


# -- arrival processes ---------------------------------------------------------

class TestArrivals:
    def test_poisson_mean_interarrival(self):
        rng = np.random.default_rng(0)
        ts = arrival_times(Arrival(process="poisson", rate_rps=50.0),
                           1500, rng)
        gaps = np.diff(ts)
        assert abs(float(np.mean(gaps)) - 1 / 50.0) < 0.1 / 50.0
        assert all(g >= 0 for g in gaps)

    def test_uniform_exact_spacing(self):
        rng = np.random.default_rng(0)
        ts = arrival_times(Arrival(process="uniform", rate_rps=10.0),
                           5, rng)
        assert ts == pytest.approx([0.0, 0.1, 0.2, 0.3, 0.4])

    def test_bursty_depth_and_gap(self):
        rng = np.random.default_rng(0)
        ts = arrival_times(Arrival(process="bursty", rate_rps=10.0,
                                   burst_depth=4), 12, rng)
        # bursts of exactly 4 share one arrival instant...
        assert ts[0:4] == [ts[0]] * 4
        assert ts[4:8] == [ts[4]] * 4
        # ...and the default gap preserves the mean rate (depth/rate).
        assert ts[4] - ts[0] == pytest.approx(0.4)

    def test_ramp_rate_increases(self):
        rng = np.random.default_rng(0)
        ts = arrival_times(Arrival(process="ramp", rate_rps=5.0,
                                   ramp_to_rps=50.0), 1000, rng)
        gaps = np.diff(ts)
        first, second = gaps[:len(gaps) // 2], gaps[len(gaps) // 2:]
        assert float(np.mean(second)) < 0.5 * float(np.mean(first))

    def test_unknown_process_rejected(self):
        with pytest.raises(ValueError, match="unknown arrival"):
            arrival_times(Arrival(process="weibull"), 4,
                          np.random.default_rng(0))


# -- prompt generation ---------------------------------------------------------

class TestPrompts:
    def test_prefix_overlap_measured(self):
        sc = Scenario(name="pfx", num_requests=64, prefix_overlap=0.6,
                      prompt_len=LengthDist(kind="fixed", value=50),
                      seed=1)
        sched = build_schedule(sc, vocab_size=256, max_prompt_len=80)
        got = measured_prefix_overlap([r.prompt_tokens for r in sched])
        assert abs(got - 0.6) < 0.05

    def test_zero_overlap_prompts_unique(self):
        sc = Scenario(name="uniq", num_requests=64, prefix_overlap=0.0,
                      prompt_len=LengthDist(kind="fixed", value=50),
                      seed=1)
        sched = build_schedule(sc, vocab_size=256, max_prompt_len=80)
        assert measured_prefix_overlap(
            [r.prompt_tokens for r in sched]) < 0.05

    def test_length_dist_clipping(self):
        rng = np.random.default_rng(0)
        d = LengthDist(kind="lognormal", mu=10.0, sigma=1.0, low=4,
                       high=1 << 20)
        for _ in range(20):
            assert 4 <= d.sample(rng, 32) <= 32    # cap wins over high

    def test_length_kinds(self):
        rng = np.random.default_rng(0)
        assert LengthDist(kind="fixed", value=7).sample(rng, 100) == 7
        assert LengthDist(kind="choice",
                          choices=(5,)).sample(rng, 100) == 5
        u = LengthDist(kind="uniform", low=3, high=6)
        assert all(3 <= u.sample(rng, 100) <= 6 for _ in range(30))

    def test_standard_matrix_shape(self):
        m = standard_matrix(num_requests=8)
        assert [s.name for s in m] == ["uniform", "bursty_qos",
                                       "shared_prefix",
                                       "mixed_interference",
                                       "multi_adapter", "multi_turn"]
        assert m[2].prefix_overlap == 0.75
        assert dict(m[1].qos_mix).keys() == {"interactive", "batch"}
        assert m[4].adapter_ids and m[4].adapter_skew == 1.0
        assert m[5].turns == 3 and m[5].think_time_s > 0
        for s in m:
            s.validate()

    def test_shared_prefix_overlap_knob(self):
        """The 0.5–0.95 overlap sweep axis: the knob must land on the
        shared_prefix scenario verbatim."""
        for f in (0.5, 0.75, 0.95):
            m = standard_matrix(num_requests=8, shared_prefix_overlap=f)
            sc = next(s for s in m if s.name == "shared_prefix")
            assert sc.prefix_overlap == f

    def test_mixed_interference_correlates_class_and_shape(self):
        """The head-of-line-blocking probe: batch requests carry LONG
        prompts, interactive ones short — per request, not just on
        average (class_profiles correlation)."""
        sc = standard_matrix(num_requests=64, prompt_len=48)[3]
        sched = build_schedule(sc, vocab_size=256, max_prompt_len=400)
        by_cls = {}
        for r in sched:
            by_cls.setdefault(r.qos, []).append(len(r.prompt_tokens))
        assert set(by_cls) == {"interactive", "batch"}
        assert max(by_cls["interactive"]) < min(by_cls["batch"]), \
            "class/shape correlation lost"
        # Determinism holds with profiles active.
        again = build_schedule(sc, vocab_size=256, max_prompt_len=400)
        assert [(r.prompt_tokens, r.qos, r.max_new_tokens)
                for r in sched] == \
               [(r.prompt_tokens, r.qos, r.max_new_tokens)
                for r in again]

    def test_class_profiles_validation(self):
        from kubeflow_tpu.loadgen import LengthDist, Scenario

        bad = Scenario(name="x", class_profiles=(
            ("gold", LengthDist(), LengthDist()),))
        with pytest.raises(ValueError, match="gold"):
            bad.validate()


class TestMultiAdapter:
    def test_zipf_skew_orders_popularity(self):
        """adapter_ids[0] is the hottest tenant under skew > 0; skew 0
        is uniform-ish; every request in an adapter scenario carries an
        id from the declared set; same seed → identical schedule."""
        ids = tuple(f"a{i}" for i in range(8))
        sc = Scenario(name="ma", num_requests=400, adapter_ids=ids,
                      adapter_skew=1.0, seed=3)
        sched = build_schedule(sc, vocab_size=256, max_prompt_len=64)
        counts = {}
        for r in sched:
            assert r.adapter in ids
            counts[r.adapter] = counts.get(r.adapter, 0) + 1
        assert counts["a0"] > counts["a7"] * 2, counts
        again = build_schedule(sc, vocab_size=256, max_prompt_len=64)
        assert [(r.prompt_tokens, r.adapter) for r in sched] == \
               [(r.prompt_tokens, r.adapter) for r in again]

    def test_adapter_free_schedules_unchanged(self):
        """Appending the adapter draw must not perturb historical
        adapter-free schedules (drawn only when adapter_ids is set)."""
        sc = Scenario(name="plain", num_requests=16, seed=5)
        sched = build_schedule(sc, vocab_size=256, max_prompt_len=64)
        assert all(r.adapter is None for r in sched)

    def test_session_mode_pins_adapter_per_session(self):
        sc = Scenario(name="s", num_requests=24, turns=3,
                      adapter_ids=("a0", "a1", "a2"), seed=1)
        sched = build_schedule(sc, vocab_size=256, max_prompt_len=64)
        by_session = {}
        for r in sched:
            by_session.setdefault(r.session, set()).add(r.adapter)
        assert all(len(s) == 1 for s in by_session.values()), \
            "a conversation must not switch tenants mid-flight"

    def test_validation(self):
        with pytest.raises(ValueError, match="unique"):
            Scenario(name="x", adapter_ids=("a", "a")).validate()
        with pytest.raises(ValueError, match="adapter_skew"):
            Scenario(name="x", adapter_ids=("a",),
                     adapter_skew=-1.0).validate()

    def test_per_adapter_report_split(self):
        """Outcomes carrying adapter ids aggregate into the per-adapter
        TTFT/TPOT block (the one-tenant-degrading attribution)."""
        from kubeflow_tpu.loadgen.runner import RequestOutcome, ScenarioRun
        from kubeflow_tpu.loadgen.report import build_report

        outs = []
        for i in range(8):
            aid = f"a{i % 2}"
            outs.append(RequestOutcome(
                idx=i, qos="standard", scheduled_t=0.0, lag_s=0.0,
                ttft_s=0.010 if aid == "a0" else 0.050,
                latency_s=0.1, tokens=8, status="ok", adapter=aid))
        run = ScenarioRun(
            scenario=Scenario(name="ma", num_requests=8,
                              adapter_ids=("a0", "a1")),
            outcomes=outs, wall_s=1.0, schedule=[])
        rep = build_report(run)
        assert set(rep["adapters"]) == {"a0", "a1"}
        assert rep["adapters"]["a0"]["ttft_ms"]["p50"] < \
            rep["adapters"]["a1"]["ttft_ms"]["p50"]
        assert rep["adapters"]["a0"]["requests"] == 4


class TestMultiTurn:
    """Session-mode schedules (Scenario.turns > 1): conversations
    re-arriving with their prior prefix + one new turn — the
    tiered-KV-cache traffic shape."""

    def _sc(self, **kw):
        base = dict(name="mt", num_requests=12, turns=3, think_time_s=0.1,
                    arrival=Arrival(process="poisson", rate_rps=4.0),
                    prompt_len=LengthDist(kind="fixed", value=24),
                    output_len=LengthDist(kind="fixed", value=4), seed=3)
        base.update(kw)
        return Scenario(**base)

    def test_session_structure(self):
        sched = build_schedule(self._sc(), vocab_size=256,
                               max_prompt_len=64)
        assert len(sched) == 12            # 4 sessions x 3 turns
        by_session: dict = {}
        for sr in sched:
            by_session.setdefault(sr.session, []).append(sr)
        assert len(by_session) == 4
        for turns in by_session.values():
            turns.sort(key=lambda r: r.turn)
            assert [r.turn for r in turns] == [0, 1, 2]
            assert turns[0].prev_idx is None and turns[0].think_s == 0.0
            for prev, cur in zip(turns, turns[1:]):
                assert cur.prev_idx == prev.idx
                assert cur.think_s == 0.1
                assert cur.t >= prev.t
                # one QoS class per conversation
                assert cur.qos == prev.qos

    def test_new_turns_are_short(self):
        sched = build_schedule(self._sc(), vocab_size=256,
                               max_prompt_len=64)
        first = [len(r.prompt_tokens) for r in sched if r.turn == 0]
        later = [len(r.prompt_tokens) for r in sched if r.turn > 0]
        assert max(later) < min(first)

    def test_session_schedule_deterministic(self):
        a = build_schedule(self._sc(), vocab_size=256, max_prompt_len=64)
        b = build_schedule(self._sc(), vocab_size=256, max_prompt_len=64)
        assert [(r.t, r.prompt_tokens, r.session, r.turn, r.prev_idx)
                for r in a] == \
               [(r.t, r.prompt_tokens, r.session, r.turn, r.prev_idx)
                for r in b]

    def test_think_validation(self):
        with pytest.raises(ValueError, match="turns"):
            self._sc(turns=0).validate()
        with pytest.raises(ValueError, match="think"):
            self._sc(think_time_s=-1.0).validate()

    def test_engine_run_composes_conversation(self):
        """E2E on a paged radix engine: every turn past the first must
        ride the conversation prefix — the radix index reports reused
        tokens, and all turns complete."""
        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import init_decoder_params
        from kubeflow_tpu.serve.engine import LLMEngine

        cfg = preset("tiny", vocab_size=512)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        engine = LLMEngine(
            cfg, BatchingSpec(max_batch_size=4, max_seq_len=128,
                              paged=True, page_size=16,
                              chunked_prefill_tokens=16, decode_steps=4),
            params=params)
        engine.start()
        try:
            sc = self._sc(num_requests=6, turns=3, think_time_s=0.01,
                          prompt_len=LengthDist(kind="fixed", value=20),
                          request_timeout_s=60.0)
            run = run_scenario(EngineTarget(engine), sc, vocab_size=256,
                               max_prompt_len=64)
            assert all(o.ok for o in run.outcomes), \
                [(o.idx, o.status) for o in run.outcomes]
            tier = engine.kv_tier_stats()
            assert tier["prefix_hits"] >= 4      # every later turn hits
            assert tier["tokens_matched"] > 0
            deadline = time.time() + 20.0
            while engine.kv_pages_in_use() > 0 and time.time() < deadline:
                time.sleep(0.05)
            assert engine.kv_pages_in_use() == 0
            engine._allocator.assert_quiescent()
        finally:
            engine.stop()


# -- the threshold gate --------------------------------------------------------

def _row(name, req_s, ttft_p95, **extra):
    row = {"scenario": name, "req_s": req_s,
           "ttft_ms": {"p50": ttft_p95 / 2, "p95": ttft_p95}}
    row.update(extra)
    return row


class TestGate:
    def test_req_s_regression_flagged(self):
        out = compare_scenario(_row("u", 10.0, 50.0),
                               _row("u", 7.0, 50.0), band_pct=20.0)
        assert out and "req/s" in out[0]

    def test_ttft_regression_flagged_with_floor(self):
        out = compare_scenario(_row("u", 10.0, 50.0),
                               _row("u", 10.0, 90.0), band_pct=20.0)
        assert out and "ttft" in out[0]
        # under the absolute floor, a huge relative move is noise
        out = compare_scenario(_row("u", 10.0, 0.5),
                               _row("u", 10.0, 2.0), band_pct=20.0,
                               ttft_floor_ms=5.0)
        assert out == []

    def test_within_band_clean(self):
        assert compare_scenario(_row("u", 10.0, 50.0),
                                _row("u", 9.0, 55.0), band_pct=20.0) == []

    def test_matrix_coverage_drift(self):
        verdict = compare_matrix([_row("a", 1, 1), _row("b", 1, 1)],
                                 [_row("a", 1, 1)], band_pct=10.0)
        assert not verdict["ok"]
        assert any("'b'" in c for c in verdict["coverage"])

    def test_matrix_attribution_diff_attached(self):
        base = _row("u", 10.0, 50.0,
                    engine={"queue_delay_p95_ms": 3.0},
                    phases={"queued_ms": {"p50": 1}})
        cand = _row("u", 4.0, 500.0,
                    engine={"queue_delay_p95_ms": 400.0},
                    phases={"queued_ms": {"p50": 300}})
        verdict = compare_matrix([base], [cand], band_pct=15.0)
        assert not verdict["ok"]
        diff = verdict["regressions"][0]["diff"]
        assert diff["engine"]["candidate"]["queue_delay_p95_ms"] == 400.0
        assert diff["engine"]["baseline"]["queue_delay_p95_ms"] == 3.0

    def test_noise_band_floor_and_cap(self):
        assert noise_band_pct([1.0]) == 10.0          # floor
        assert noise_band_pct([20.0]) == 40.0         # 2x spread
        assert noise_band_pct([90.0]) == 60.0         # cap
        assert spread_pct(10.0, 8.0) == pytest.approx(20.0)
        assert spread_pct(0.0, 0.0) == 0.0

    def test_matrix_requires_band(self):
        with pytest.raises(ValueError, match="noise band"):
            compare_matrix([_row("a", 1, 1)], [_row("a", 1, 1)])


# -- end-to-end against a real engine ------------------------------------------

@pytest.fixture(scope="module")
def scenario_engine():
    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.models.decoder import init_decoder_params
    from kubeflow_tpu.serve.engine import LLMEngine

    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(
        cfg, BatchingSpec(max_batch_size=4, max_seq_len=128,
                          prefill_buckets=[16, 32], decode_steps=4),
        params=params)
    engine.start()
    yield engine, cfg
    engine.stop()


class TestEndToEnd:
    def test_scenario_run_full_attribution(self, scenario_engine):
        engine, cfg = scenario_engine
        from kubeflow_tpu.serve.server import serving_metrics_registry

        sc = Scenario(
            name="e2e", num_requests=8,
            arrival=Arrival(process="poisson", rate_rps=30.0),
            prompt_len=LengthDist(kind="fixed", value=12),
            output_len=LengthDist(kind="fixed", value=4),
            qos_mix=(("interactive", 1.0), ("batch", 1.0)),
            slo_ttft_ms=60_000.0, request_timeout_s=60.0, seed=5)
        run = run_scenario(EngineTarget(engine), sc,
                           vocab_size=cfg.vocab_size, max_prompt_len=100,
                           tracer=TRACER)
        assert len(run.outcomes) == 8
        assert all(o.status == "ok" for o in run.outcomes)
        text = serving_metrics_registry([("e2e", engine)]).render()
        rep = build_report(run, metrics_text=text, tracer=TRACER)
        assert rep["req_s"] > 0
        assert rep["ttft_ms"]["p95"] > 0
        assert rep["goodput"]["ratio"] == 1.0
        # engine attribution joined off the real exposition
        assert rep["engine"]["requests_completed"] >= 8
        assert "queue_delay_p95_ms" in rep["engine"]
        assert {"interactive", "batch"} <= set(rep["engine"]["qos"])
        # per-phase span breakdown covers every traced request
        assert rep["phases"]["trace_coverage"] == 8
        assert rep["phases"]["decode_ms"]["p95"] > 0
        # quiescence: a full scenario run leaves no open spans
        assert TRACER.open_spans() == 0

    def test_overload_shed_reported(self, scenario_engine):
        engine, cfg = scenario_engine
        engine.max_queue, old = 2, engine.max_queue
        try:
            sc = Scenario(
                name="overload", num_requests=16,
                arrival=Arrival(process="bursty", rate_rps=100.0,
                                burst_depth=16),
                prompt_len=LengthDist(kind="fixed", value=12),
                output_len=LengthDist(kind="fixed", value=4),
                request_timeout_s=60.0, seed=6)
            run = run_scenario(EngineTarget(engine), sc,
                               vocab_size=cfg.vocab_size,
                               max_prompt_len=100, tracer=TRACER)
            rep = build_report(run, tracer=TRACER)
            assert rep["by_status"].get("shed", 0) >= 1
            assert rep["goodput"]["ratio"] < 1.0    # sheds count offered
            assert TRACER.open_spans() == 0
        finally:
            engine.max_queue = old

    def test_report_registry_lints_and_parses(self, scenario_engine):
        from kubeflow_tpu.obs.registry import parse_exposition

        reports = [
            {"scenario": "a", "requests": 4, "by_status": {"ok": 4},
             "req_s": 2.0, "offered_req_s": 2.5,
             "ttft_ms": {"p50": 5.0, "p95": 9.0},
             "tpot_ms": {"p50": 1.0},
             "goodput": {"ratio": 1.0, "slo_ttft_ms": 100.0},
             "schedule_lag_ms": {"p50": 0.1, "p95": 0.4}},
            {"scenario": "b", "requests": 4,
             "by_status": {"ok": 2, "shed": 2}, "req_s": 1.0,
             "offered_req_s": 2.5, "ttft_ms": {}, "tpot_ms": {}},
        ]
        reg = report_registry(reports)
        assert reg.lint() == []
        samples = parse_exposition(reg.render())
        by_name = {}
        for name, labels, value in samples:
            by_name.setdefault(name, {})[labels.get("scenario")] = value
        assert by_name["kftpu_loadgen_requests_total"]["a"] == 4
        assert by_name["kftpu_loadgen_requests_failed_total"]["b"] == 2
        assert by_name["kftpu_loadgen_ttft_p95_ms"]["a"] == 9.0
        assert by_name["kftpu_loadgen_goodput_ratio"]["a"] == 1.0

    def test_attribution_series_all_produced(self, scenario_engine):
        """The loadgen's scrape set must exist in a REAL rendered
        exposition — the producer half of the contract the X7xx lint
        checks statically (a renamed engine series fails here even if
        the AST extraction drifts). Two producers: the model server's
        registry (engine/serving series) and the fleet observability
        registry (kftpu_fleet_*/kftpu_obs_* — obs/fleet.py)."""
        engine, cfg = scenario_engine
        from kubeflow_tpu.obs.fleet import (
            FleetTraceCollector, MetricsHistory, fleet_obs_registry,
        )
        from kubeflow_tpu.obs.registry import parse_exposition
        from kubeflow_tpu.serve.server import serving_metrics_registry

        text = serving_metrics_registry([("pin", engine)]).render()
        names = {n for n, _, _ in parse_exposition(text)}
        fleet = fleet_obs_registry(collector=FleetTraceCollector(),
                                   history=MetricsHistory()).render()
        names |= {n for n, _, _ in parse_exposition(fleet)}
        missing = [s for s in ATTRIBUTION_SERIES if s not in names]
        assert not missing, f"attribution series not rendered: {missing}"


# -- trace phase rollups -------------------------------------------------------

class TestPhases:
    def _spans(self):
        return [
            {"name": "engine.queued", "duration_ms": 4.0},
            {"name": "engine.queued", "duration_ms": 1.0},   # requeue
            {"name": "engine.prefill", "duration_ms": 10.0},
            {"name": "engine.decode", "duration_ms": 30.0},
            {"name": "server.request", "duration_ms": 50.0},
            {"name": "engine.decode", "duration_ms": None},  # still open
        ]

    def test_phase_durations_sums_per_phase(self):
        from kubeflow_tpu.obs.trace import phase_durations

        ph = phase_durations(self._spans())
        assert ph == {"queued_ms": 5.0, "prefill_ms": 10.0,
                      "decode_ms": 30.0}

    def test_debug_payload_carries_phases(self):
        from kubeflow_tpu.obs.trace import debug_traces_payload

        t = Tracer()
        with t.span("server.request") as root:
            sp = t.start_span("engine.queued", parent=root)
            sp.end()
            sp = t.start_span("engine.decode", parent=root)
            sp.end()
        doc = debug_traces_payload("/debug/traces?slowest=2", tracer=t)
        assert doc["traces"][0]["phases"].keys() == {"queued_ms",
                                                     "decode_ms"}

    def test_format_dump_prints_phase_rollup(self):
        from kubeflow_tpu.obs.trace import debug_traces_payload, format_dump

        t = Tracer()
        with t.span("server.request") as root:
            sp = t.start_span("engine.decode", parent=root)
            sp.end()
        doc = debug_traces_payload("/debug/traces", tracer=t)
        out = format_dump(doc)
        assert "decode=" in out and "ms]" in out

    def test_no_engine_spans_no_phase_key(self):
        from kubeflow_tpu.obs.trace import debug_traces_payload

        t = Tracer()
        with t.span("pipeline.run"):
            pass
        doc = debug_traces_payload("/debug/traces", tracer=t)
        assert "phases" not in doc["traces"][0]


def test_tokens_to_text_preserves_structure():
    from kubeflow_tpu.loadgen import tokens_to_text

    a = tokens_to_text((1, 2, 3, 4))
    b = tokens_to_text((1, 2, 9, 9))
    assert len(a) == 4
    assert a[:2] == b[:2] and a[2:] != b[2:]
    assert math.isfinite(len(a))
