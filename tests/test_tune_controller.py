"""Experiment/Trial controller semantics, envtest-style (no processes):
tests drive trial-job worker phases by hand, like the reference's katib
controller tests against envtest (SURVEY.md §4.2, §3.3)."""

import json
import os

import pytest

from kubeflow_tpu.core.jobs import JAXJob, Worker, WorkerPhase
from kubeflow_tpu.core.tuning import Experiment, Suggestion, Trial
from kubeflow_tpu.operator.control_plane import ControlPlane, ControlPlaneConfig
from kubeflow_tpu.runtime.topology import Cluster, SliceTopology
from kubeflow_tpu.tune.client import build_experiment, parameter
from kubeflow_tpu.tune.experiment_controller import substitute_parameters
from kubeflow_tpu.tune.trial_controller import LABEL_EXPERIMENT


@pytest.fixture()
def cp(tmp_path):
    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="v5e",
                                              dims=(2, 2))]),
        launch_processes=False,
        metrics_sync_interval=None,
    ))
    yield plane


def experiment_of(**kw) -> Experiment:
    defaults = dict(
        entrypoint="objective_probe",
        parameters=[parameter("x", min=-1.0, max=1.0),
                    parameter("y", min=-1.0, max=1.0)],
        objective_metric="objective",
        algorithm="random",
        algorithm_settings={"random_state": 0},
        max_trial_count=4,
        parallel_trial_count=2,
    )
    defaults.update(kw)
    return build_experiment("hpo", **defaults)


def quad(params):
    return (params["x"] - 0.3) ** 2 + (params["y"] + 0.2) ** 2


def write_metrics(cp, job_name, series, namespace="default"):
    """Put a metrics.jsonl where the trial's file collector looks."""
    workdir = os.path.join(cp.config.base_dir, namespace, job_name, "worker-0")
    os.makedirs(workdir, exist_ok=True)
    with open(os.path.join(workdir, "metrics.jsonl"), "w") as f:
        for step, value in series:
            f.write(json.dumps({"step": step, "objective": value}) + "\n")


def drive_trials(cp, value_fn=quad, *, fail=False, limit=None):
    """Complete every unfinished trial job: write its metrics, drive workers.

    Returns how many jobs were driven."""
    n = 0
    for trial in cp.store.list(Trial):
        if trial.status.has_condition("Succeeded") or trial.status.has_condition("Failed"):
            continue
        job = cp.store.try_get(JAXJob, trial.metadata.name)
        if job is None:
            continue
        workers = cp.store.list(Worker, label_selector={
            "training.tpu.kubeflow.dev/job-name": job.metadata.name})
        if not workers:
            continue
        if not fail:
            v = value_fn(trial.spec.parameter_assignments)
            write_metrics(cp, job.metadata.name,
                          [(0, v + 0.2), (1, v + 0.1), (2, v)])
        for w in workers:
            w = cp.store.get(Worker, w.metadata.name, w.metadata.namespace)
            w.status.phase = WorkerPhase.FAILED if fail else WorkerPhase.SUCCEEDED
            w.status.exit_code = 1 if fail else 0
            cp.store.update_status(w)
        n += 1
        if limit and n >= limit:
            break
    return n


def pump(cp, rounds=30, **drive_kw):
    """step → drive → step until the experiment finishes or rounds out."""
    for _ in range(rounds):
        cp.step()
        exp = cp.store.try_get(Experiment, "hpo")
        if exp is None or exp.status.has_condition("Succeeded") \
                or exp.status.has_condition("Failed"):
            return exp
        drive_trials(cp, **drive_kw)
    return cp.store.try_get(Experiment, "hpo")


class TestExperimentLifecycle:
    def test_random_completes_with_optimal(self, cp):
        cp.submit(experiment_of())
        exp = pump(cp)
        assert exp.status.has_condition("Succeeded")
        assert exp.status.trials_succeeded == 4
        opt = exp.status.current_optimal_trial
        assert opt.trial_name is not None
        assert opt.objective_value == pytest.approx(
            quad(opt.parameter_assignments))
        # optimal really is the min over all trials
        finals = [t.status.final_objective for t in cp.store.list(Trial)
                  if t.status.final_objective is not None]
        assert opt.objective_value == pytest.approx(min(finals))

    def test_parallelism_respected(self, cp):
        cp.submit(experiment_of(max_trial_count=6, parallel_trial_count=2))
        cp.step()
        jobs = cp.store.list(JAXJob)
        assert len(jobs) == 2  # never more than parallel_trial_count at once

    @pytest.mark.slow  # tier-1 budget (ISSUE 14): slowest fast tests re-marked
    def test_goal_finishes_early(self, cp):
        # Any trial beats a goal of 10 → finish after the first wave.
        cp.submit(experiment_of(goal=10.0, max_trial_count=12))
        exp = pump(cp)
        assert exp.status.has_condition("Succeeded")
        assert exp.status.trials < 12
        running = [t for t in cp.store.list(Trial)
                   if not (t.status.has_condition("Succeeded")
                           or t.status.has_condition("Failed"))]
        assert running == []  # stragglers reaped on completion

    def test_failures_fail_experiment(self, cp):
        exp = experiment_of(max_trial_count=4, parallel_trial_count=1,
                            max_failed_trial_count=0)
        # Make worker failures terminal (no retries) for determinism.
        worker = exp.spec.trial_template.manifest["spec"]["replica_specs"]["worker"]
        worker["restart_policy"] = "Never"
        cp.submit(exp)
        exp = pump(cp, fail=True)
        assert exp.status.has_condition("Failed")
        assert exp.status.trials_failed >= 1

    def test_suggestion_holds_state_and_assignments(self, cp):
        cp.submit(experiment_of())
        pump(cp)
        sugg = cp.store.get(Suggestion, "hpo")
        assert sugg.spec.requests == 4
        assert len(sugg.status.assignments) == 4
        json.dumps(sugg.status.algorithm_state)

    def test_trials_labeled_and_owned(self, cp):
        cp.submit(experiment_of())
        cp.step()
        trials = cp.store.list(Trial, label_selector={LABEL_EXPERIMENT: "hpo"})
        assert trials and all(t.metadata.owner == "Experiment/default/hpo"
                              or "hpo" in t.metadata.owner for t in trials)


class TestMaximize:
    @pytest.mark.slow   # ~9s: the minimize loop covers the machinery
    def test_maximize_objective(self, cp):
        cp.submit(experiment_of(objective_type="maximize"))
        exp = pump(cp, value_fn=lambda p: -quad(p))
        assert exp.status.has_condition("Succeeded")
        finals = [t.status.final_objective for t in cp.store.list(Trial)
                  if t.status.final_objective is not None]
        assert exp.status.current_optimal_trial.objective_value == pytest.approx(
            max(finals))


class TestEarlyStopping:
    def test_medianstop_prunes(self, cp):
        exp = experiment_of(max_trial_count=6, parallel_trial_count=1,
                            early_stopping=True)
        exp.spec.early_stopping.settings = {"min_trials_required": 3}
        cp.submit(exp)
        # Complete 3 good trials.
        for _ in range(20):
            cp.step()
            exp_now = cp.store.get(Experiment, "hpo")
            if exp_now.status.trials_succeeded >= 3:
                break
            drive_trials(cp, value_fn=lambda p: 0.1)
        # Next trial reports terrible metrics but keeps running.
        cp.step()
        bad = [t for t in cp.store.list(Trial)
               if not (t.status.has_condition("Succeeded")
                       or t.status.has_condition("Failed"))]
        assert bad
        write_metrics(cp, bad[0].metadata.name, [(0, 50.0), (1, 50.0)])
        for _ in range(10):
            cp.step()
            t = cp.store.try_get(Trial, bad[0].metadata.name)
            if t is not None and t.status.has_condition("Succeeded"):
                break
        t = cp.store.get(Trial, bad[0].metadata.name)
        assert t.status.pruned
        exp_now = cp.store.get(Experiment, "hpo")
        assert exp_now.status.trials_pruned >= 1
        # Pruned trial's job was stopped.
        assert cp.store.try_get(JAXJob, bad[0].metadata.name) is None


class TestCollectors:
    def test_file_collector_skips_garbage(self, tmp_path):
        from kubeflow_tpu.tune.metrics import collect_file

        p = tmp_path / "metrics.jsonl"
        p.write_text(
            '{"step": 0, "objective": 1.5}\n'
            'not json\n'
            '{"step": "warmup", "objective": 2.0}\n'
            '{"step": 1, "objective": "NaN-ish"}\n'
            '{"step": 2, "objective": 0.5}\n')
        out = collect_file(str(p), {"objective"})
        assert out == {"objective": [(0, 1.5), (2, 0.5)]}

    def test_stdout_collector(self, tmp_path):
        from kubeflow_tpu.tune.metrics import collect_stdout

        p = tmp_path / "w.log"
        p.write_text(
            "epoch done loss=0.9 acc=0.1\n"
            "noise line\n"
            "step=5 loss=0.4\n")
        out = collect_stdout(str(p), {"loss"})
        assert out == {"loss": [(0, 0.9), (5, 0.4)]}

    def test_explicit_metrics_file_relative(self, cp, tmp_path):
        from kubeflow_tpu.core.jobs import JAXJob, JAXJobSpec, ReplicaSpec, \
            WorkloadSpec
        from kubeflow_tpu.core.object import ObjectMeta
        from kubeflow_tpu.tune.metrics import collect

        job = JAXJob(metadata=ObjectMeta(name="j"), spec=JAXJobSpec(
            replica_specs={"worker": ReplicaSpec(
                template=WorkloadSpec(entrypoint="noop"))}))
        jdir = os.path.join(cp.config.base_dir, "default", "j")
        os.makedirs(jdir, exist_ok=True)
        with open(os.path.join(jdir, "my.jsonl"), "w") as f:
            f.write(json.dumps({"step": 0, "objective": 3.0}) + "\n")
        out = collect("file", job=job, job_dir=jdir,
                      metric_names={"objective"}, metrics_file="my.jsonl")
        assert out == {"objective": [(0, 3.0)]}


class TestSubstitution:
    def test_typed_exact_and_embedded(self):
        manifest = {
            "a": "${trialParameters.lr}",
            "b": "lr=${trialParameters.lr}!",
            "c": ["${trialParameters.n}", {"d": "${trialName}"}],
        }
        out = substitute_parameters(manifest, {"lr": 0.01, "n": 4}, "t-0")
        assert out["a"] == 0.01          # typed, not stringified
        assert out["b"] == "lr=0.01!"
        assert out["c"][0] == 4
        assert out["c"][1]["d"] == "t-0"

    def test_no_placeholder_untouched(self):
        src = {"x": 1, "y": "plain"}
        assert substitute_parameters(src, {"lr": 1}, "t") == src


# -- collector kinds: tfevent + prometheus ((U) katib metricscollector) -------

def _write_tfevent(path, records):
    """Minimal tf.summary scalar event writer (TFRecord + protobuf wire
    format) — the inverse of metrics.collect_tfevent's reader."""
    import struct

    def varint(n):
        out = b""
        while True:
            b7 = n & 0x7F
            n >>= 7
            out += bytes([b7 | (0x80 if n else 0)])
            if not n:
                return out

    def field(num, wire, payload):
        return varint((num << 3) | wire) + payload

    frames = b""
    for step, tag, value in records:
        tag_b = tag.encode()
        val_msg = (field(1, 2, varint(len(tag_b)) + tag_b)
                   + field(2, 5, struct.pack("<f", value)))
        summary = field(1, 2, varint(len(val_msg)) + val_msg)
        event = field(2, 0, varint(step)) + field(
            5, 2, varint(len(summary)) + summary)
        frames += (struct.pack("<Q", len(event)) + b"\x00" * 4 + event
                   + b"\x00" * 4)
    with open(path, "wb") as f:
        f.write(frames)


def test_tfevent_collector(tmp_path):
    from kubeflow_tpu.tune.metrics import collect_tfevent

    logdir = tmp_path / "tb"
    logdir.mkdir()
    _write_tfevent(str(logdir / "events.out.tfevents.123.host"), [
        (0, "loss", 2.5), (0, "accuracy", 0.1),
        (10, "loss", 1.5), (20, "loss", 1.1), (20, "ignored", 9.0),
    ])
    got = collect_tfevent(str(logdir), {"loss", "accuracy"})
    assert got["loss"] == [(0, 2.5), (10, 1.5), (20, pytest.approx(1.1))]
    assert got["accuracy"] == [(0, pytest.approx(0.1))]


def test_tfevent_collector_tolerates_truncated_tail(tmp_path):
    from kubeflow_tpu.tune.metrics import collect_tfevent

    p = tmp_path / "events.out.tfevents.1.h"
    _write_tfevent(str(p), [(0, "loss", 2.0), (5, "loss", 1.0)])
    data = p.read_bytes()
    p.write_bytes(data[:-7])   # live trial mid-append
    got = collect_tfevent(str(p), {"loss"})
    assert got["loss"][0] == (0, 2.0)


def test_prometheus_collector(tmp_path):
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from kubeflow_tpu.tune.metrics import collect_prometheus

    body = (b"# HELP loss training loss\n"
            b"loss{replica=\"0\"} 0.75 1700000000123\n"   # trailing timestamp
            b"tokens_total 12345\n"
            b"malformed_line\n")

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}/metrics"
        got = collect_prometheus(url, {"loss", "tokens_total"}, step=7)
        assert got == {"loss": [(7, 0.75)], "tokens_total": [(7, 12345.0)]}
        assert collect_prometheus("http://127.0.0.1:1/none", {"loss"}) == {}
    finally:
        srv.shutdown()


def test_tfevent_collector_skips_corrupt_frame(tmp_path):
    import struct

    from kubeflow_tpu.tune.metrics import collect_tfevent

    p = tmp_path / "events.out.tfevents.09.h"
    _write_tfevent(str(p), [(0, "loss", 2.0)])
    # Append a frame whose length is intact but whose payload is a
    # truncated varint (worst-case partial flush).
    bad = b"\xff\xff\xff"
    with open(p, "ab") as f:
        f.write(struct.pack("<Q", len(bad)) + b"\0" * 4 + bad + b"\0" * 4)
    _write_tfevent(str(tmp_path / "events.out.tfevents.10.h"),
                   [(5, "loss", 1.0)])
    got = collect_tfevent(str(tmp_path), {"loss"})
    assert got["loss"] == [(0, 2.0), (5, 1.0)]
