"""Continuous-batching engine correctness: slot decode must reproduce the
full-forward greedy path exactly (the serving analog of sharded-vs-unsharded
numerics tests, SURVEY.md §4 rebuild translation (d))."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import decoder_forward, init_decoder_params
from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams


@pytest.fixture(scope="module")
def cfg():
    return preset("tiny")


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def engine(cfg, params):
    return LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=4, max_seq_len=96,
                     prefill_buckets=[16, 32, 64]),
        params=params)


def reference_greedy(params, cfg, prompt, n_new):
    """Argmax continuation by full re-forward each step (no cache)."""
    toks = list(prompt)
    for _ in range(n_new):
        logits, _, _ = decoder_forward(
            params, jnp.asarray([toks], jnp.int32), cfg)
        toks.append(int(jax.device_get(jnp.argmax(logits[0, -1]))))
    return toks[len(prompt):]


@pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
def test_single_request_matches_full_forward(engine, params, cfg):
    prompt = [5, 17, 3, 99, 42]
    got = engine.generate(prompt, SamplingParams(max_new_tokens=12))
    want = reference_greedy(params, cfg, prompt, 12)
    assert got == want


@pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
def test_interleaved_requests_match_solo(engine, params, cfg):
    """Requests admitted mid-decode of others must not perturb each other."""
    prompts = [[1, 2, 3], [7] * 20, [9, 8, 7, 6, 5, 4], [30, 31]]
    want = [reference_greedy(params, cfg, p, 8) for p in prompts]

    # Stagger: submit 0 and 1, decode a bit, then 2 and 3 join.
    reqs = [engine.submit(prompts[0], SamplingParams(max_new_tokens=8)),
            engine.submit(prompts[1], SamplingParams(max_new_tokens=8))]
    for _ in range(3):
        engine.step()
    reqs += [engine.submit(prompts[2], SamplingParams(max_new_tokens=8)),
             engine.submit(prompts[3], SamplingParams(max_new_tokens=8))]
    while not all(r.done.is_set() for r in reqs):
        engine.step()
    for r, w in zip(reqs, want):
        assert r.output_tokens == w


@pytest.mark.slow  # tier-1 budget (ISSUE 12): >10s on the gate host
def test_slot_reuse_is_clean(engine, params, cfg):
    """A slot freed by a long request must serve a short one untainted."""
    long = engine.generate([2] * 40, SamplingParams(max_new_tokens=10))
    short = engine.generate([11, 12], SamplingParams(max_new_tokens=6))
    assert short == reference_greedy(params, cfg, [11, 12], 6)
    assert long == reference_greedy(params, cfg, [2] * 40, 10)


def test_stop_token_and_metrics(engine):
    req = engine.submit([3, 1, 4], SamplingParams(max_new_tokens=50))
    while not req.done.is_set():
        engine.step()
    # force a stop-token run: use the first emitted token as the stop token
    stop = req.output_tokens[0]
    req2 = engine.submit([3, 1, 4], SamplingParams(max_new_tokens=50,
                                                   stop_token=stop))
    while not req2.done.is_set():
        engine.step()
    assert req2.finish_reason == "stop"
    assert req2.output_tokens[-1] == stop
    snap = engine.metrics.snapshot()
    assert snap["requests_completed"] >= 2
    assert snap["ttft_p50_ms"] > 0
    assert req.ttft is not None and req.ttft > 0


def test_background_loop_and_streaming(cfg, params):
    eng = LLMEngine(cfg, BatchingSpec(max_batch_size=2, max_seq_len=64,
                                      prefill_buckets=[16]), params=params)
    eng.start()
    try:
        req = eng.submit([8, 6, 4], SamplingParams(max_new_tokens=5))
        streamed = []
        while True:
            tok = req.stream.get(timeout=30)
            if tok is None:
                break
            streamed.append(tok)
        assert streamed == req.output_tokens
        assert len(streamed) == 5
    finally:
        eng.stop()


def test_sampling_respects_temperature(engine):
    """temperature>0 with a fixed engine rng still yields valid tokens and
    differs across draws (smoke, not a statistical test)."""
    outs = {tuple(engine.generate([1, 2, 3, 4],
                                  SamplingParams(max_new_tokens=6,
                                                 temperature=1.5, top_k=50)))
            for _ in range(4)}
    assert len(outs) > 1
    assert all(0 <= t < engine.cfg.vocab_size for o in outs for t in o)


class TestMultiStepDecode:
    """K decode steps per dispatch must be invisible to outputs: greedy
    streams match the single-step engine exactly, stop/budget rules fire
    mid-dispatch, and per-slot sampling params are honored."""

    def make_engine(self, cfg, params, decode_steps):
        return LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=96, prefill_buckets=[16, 32, 64],
            decode_steps=decode_steps), params=params)

    def test_matches_single_step_greedy(self, cfg, params):
        prompts = [[5, 17, 3], [7] * 12, [1, 2]]
        outs = []
        for k in (1, 4):
            eng = self.make_engine(cfg, params, k)
            reqs = [eng.submit(p, SamplingParams(max_new_tokens=n))
                    for p, n in zip(prompts, (11, 6, 3))]
            while not all(r.done.is_set() for r in reqs):
                eng.step()
            outs.append([list(r.output_tokens) for r in reqs])
        assert outs[0] == outs[1]

    def test_stop_token_mid_dispatch(self, cfg, params):
        eng = self.make_engine(cfg, params, 8)
        probe = eng.generate([3, 1, 4], SamplingParams(max_new_tokens=8))
        stop = probe[3]                    # fires mid-way through a dispatch
        req = eng.submit([3, 1, 4], SamplingParams(max_new_tokens=50,
                                                   stop_token=stop))
        while not req.done.is_set():
            eng.step()
        assert req.finish_reason == "stop"
        assert req.output_tokens == probe[:4]

    def test_budget_honored_mid_dispatch(self, cfg, params):
        eng = self.make_engine(cfg, params, 8)
        req = eng.submit([9, 9, 2], SamplingParams(max_new_tokens=5))
        while not req.done.is_set():
            eng.step()
        assert len(req.output_tokens) == 5
        assert req.finish_reason == "length"


class TestPerSlotSampling:
    """Each slot's temperature/top_k/top_p apply to that slot alone."""

    def test_top_k_not_shared_across_slots(self, cfg, params):
        """A top_k=1 slot decoding next to a top_k=0 (full categorical) slot
        must still sample greedily — round-1 took max(top_k) over the batch,
        silently truncating every slot alike."""
        from kubeflow_tpu.serve.engine import _sample_batch

        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(2, 64)) * 3, jnp.float32)
        argmaxes = np.asarray(jax.device_get(jnp.argmax(logits, axis=-1)))
        temps = jnp.asarray([1.0, 1.0], jnp.float32)
        top_k = jnp.asarray([1, 0], jnp.int32)
        top_p = jnp.asarray([1.0, 1.0], jnp.float32)
        row0, row1 = set(), set()
        for i in range(64):
            got = np.asarray(jax.device_get(_sample_batch(
                logits, jax.random.PRNGKey(i), temps, top_k, top_p)))
            row0.add(int(got[0]))
            row1.add(int(got[1]))
        assert row0 == {int(argmaxes[0])}   # top_k=1 == greedy, every draw
        assert len(row1) > 4                # full categorical explores

    def test_top_p_nucleus(self):
        from kubeflow_tpu.serve.engine import _sample_batch

        # Probabilities ~ [0.5, 0.3, 0.2]: top_p=0.6 keeps {0, 1} only
        # (exclusive cumsum: 0.0, 0.5 < 0.6, 0.8 ≥ 0.6).
        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.2]], jnp.float32))
        seen = set()
        for i in range(100):
            got = _sample_batch(logits, jax.random.PRNGKey(i),
                                jnp.asarray([1.0]), jnp.asarray([0]),
                                jnp.asarray([0.6]))
            seen.add(int(jax.device_get(got)[0]))
        assert seen == {0, 1}

    def test_temperature_zero_is_greedy_per_slot(self):
        from kubeflow_tpu.serve.engine import _sample_batch

        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.normal(size=(2, 32)), jnp.float32)
        got = _sample_batch(logits, jax.random.PRNGKey(0),
                            jnp.asarray([0.0, 0.0]), jnp.asarray([0, 0]),
                            jnp.asarray([1.0, 1.0]))
        assert np.array_equal(np.asarray(jax.device_get(got)),
                              np.asarray(jax.device_get(
                                  jnp.argmax(logits, axis=-1))))


class TestChunkedPrefill:
    """Chunked prefill: long prompts stream through fixed chunks with decode
    interleaving, producing the same output as one-shot prefill."""

    def make_engine(self, chunk):
        cfg = preset("tiny", vocab_size=512)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        return LLMEngine(cfg, BatchingSpec(
            max_batch_size=2, max_seq_len=128,
            prefill_buckets=[16, 64], chunked_prefill_tokens=chunk),
            params=params)

    def test_matches_one_shot(self):
        prompt = list(range(1, 50))          # 49 tokens
        params = SamplingParams(max_new_tokens=6, temperature=0.0)
        outs = []
        for chunk in (0, 16):                # 0 = disabled (one-shot)
            eng = self.make_engine(chunk)
            req = eng.submit(prompt, params)
            for _ in range(200):
                eng.step()
                if req.done.is_set():
                    break
            assert req.done.is_set()
            outs.append(list(req.output_tokens))
        assert outs[0] == outs[1], outs      # greedy: must match exactly

    def test_decode_interleaves_during_long_prefill(self):
        eng = self.make_engine(16)
        short = eng.submit(list(range(1, 9)),
                           SamplingParams(max_new_tokens=40, temperature=0.0))
        eng.step()                           # short admitted + first decode
        produced_before = len(short.output_tokens)
        long_req = eng.submit(list(range(1, 60)),
                              SamplingParams(max_new_tokens=4,
                                             temperature=0.0))
        # While the long prompt chunks through, the short stream keeps
        # producing tokens every step.
        for _ in range(3):
            eng.step()
        assert len(short.output_tokens) >= produced_before + 3
        for _ in range(200):
            eng.step()
            if long_req.done.is_set() and short.done.is_set():
                break
        assert long_req.done.is_set() and short.done.is_set()
        assert len(long_req.output_tokens) == 4

    def test_interleaved_decode_does_not_corrupt_chunked_kv(self):
        """Decode dispatches running while a chunked prefill holds its slot
        must leave that slot's already-written KV untouched: the chunked
        request's greedy output must equal the solo one-shot output.
        (Regression: placeholder rows once wrote KV at position 0.)"""
        long_prompt = list(range(7, 56))     # prompt[0] != 0 matters here
        want = None
        eng = self.make_engine(0)            # one-shot oracle, no traffic
        solo = eng.submit(long_prompt,
                          SamplingParams(max_new_tokens=6, temperature=0.0))
        for _ in range(200):
            eng.step()
            if solo.done.is_set():
                break
        want = list(solo.output_tokens)

        eng = self.make_engine(16)
        short = eng.submit([9, 8, 7],
                           SamplingParams(max_new_tokens=60, temperature=0.0))
        eng.step()                           # short admitted and decoding
        long_req = eng.submit(long_prompt,
                              SamplingParams(max_new_tokens=6,
                                             temperature=0.0))
        for _ in range(300):
            eng.step()                       # decode interleaves every chunk
            if long_req.done.is_set():
                break
        assert long_req.done.is_set()
        assert list(long_req.output_tokens) == want

    def test_slot_reserved_during_chunking(self):
        eng = self.make_engine(16)           # 2 slots
        long_req = eng.submit(list(range(1, 60)),
                              SamplingParams(max_new_tokens=2))
        eng.step()                           # chunk 1 of the long prompt
        s1 = eng.submit(list(range(1, 5)), SamplingParams(max_new_tokens=2))
        s2 = eng.submit(list(range(1, 5)), SamplingParams(max_new_tokens=2))
        for _ in range(200):
            eng.step()
            if long_req.done.is_set() and s1.done.is_set() and s2.done.is_set():
                break
        assert long_req.done.is_set() and s1.done.is_set() and s2.done.is_set()


class TestBatchedPrefill:
    """Batched prefill (round-5 serving lever): same-bucket one-shot
    admissions share a dispatch; outputs are exactly the sequential
    path's (rows are attention-independent)."""

    def _gen_all(self, engine, prompts, max_new=8):
        from kubeflow_tpu.serve.engine import SamplingParams

        sp = SamplingParams(max_new_tokens=max_new, temperature=0.0)
        reqs = [engine.submit(list(p), sp) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            engine.step()
        return [r.output_tokens for r in reqs]

    def test_batched_matches_sequential(self):
        import jax

        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import init_decoder_params
        from kubeflow_tpu.serve.engine import LLMEngine

        cfg = preset("tiny", param_dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        prompts = [[3, 1, 4], [1, 5, 9, 2], [6, 5], [3, 5, 8, 9, 7],
                   [2, 7, 1]]

        def make(batch_max):
            return LLMEngine(cfg, BatchingSpec(
                max_batch_size=8, max_seq_len=64, prefill_buckets=[8],
                prefill_batch_max=batch_max, decode_steps=4), params=params)

        out_b = self._gen_all(make(4), prompts)
        out_s = self._gen_all(make(1), prompts)
        assert out_b == out_s

    def test_mixed_buckets_group_separately(self):
        import jax

        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import init_decoder_params
        from kubeflow_tpu.serve.engine import LLMEngine

        cfg = preset("tiny", param_dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(1), cfg)
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=8, max_seq_len=64, prefill_buckets=[4, 16],
            prefill_batch_max=4, decode_steps=4), params=params)
        prompts = [[1, 2], [9, 9, 9, 9, 9, 9], [3], [8, 8, 8, 8, 8]]
        outs = self._gen_all(eng, prompts)
        assert all(len(o) == 8 for o in outs)

    def test_dispatch_moe_prefill_stays_unbatched(self):
        """Co-batched dispatch-MoE prompts would couple through capacity
        buffers — the engine forces the group size to 1 there."""
        import jax

        from kubeflow_tpu.core.serving import BatchingSpec
        from kubeflow_tpu.models.config import preset
        from kubeflow_tpu.models.decoder import init_decoder_params
        from kubeflow_tpu.serve.engine import LLMEngine

        cfg = preset("tiny-moe", param_dtype="float32")
        params = init_decoder_params(jax.random.PRNGKey(2), cfg)
        eng = LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=64, prefill_buckets=[8],
            prefill_batch_max=4, moe_prefill_impl="dispatch"),
            params=params)
        assert eng.prefill_batch_max == 1
        dense = LLMEngine(cfg, BatchingSpec(
            max_batch_size=4, max_seq_len=64, prefill_buckets=[8],
            prefill_batch_max=4, moe_prefill_impl="dense"), params=params)
        assert dense.prefill_batch_max == 4
