"""Test configuration: force an 8-device virtual CPU platform.

Tests validate multi-chip sharding semantics without TPU hardware by running
JAX on 8 virtual CPU devices (the driver separately dry-runs the multi-chip
path; bench.py runs on the real chip). Must run before jax initializes."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon sitecustomize force-sets the jax config to "axon,cpu", which beats
# the env var — override it back so tests run on the 8-device virtual CPU mesh.
# Guarded so the jax-free core tests still collect on a box without jax.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

import pytest  # noqa: E402


@pytest.fixture()
def store():
    from kubeflow_tpu.core.store import ObjectStore

    return ObjectStore()


@pytest.fixture()
def tiny_job():
    """A minimal valid JAXJob for controller tests."""
    from kubeflow_tpu.core.jobs import (
        JAXJob, JAXJobSpec, ReplicaSpec, WorkloadSpec, ParallelismSpec,
        TPUResourceSpec,
    )
    from kubeflow_tpu.core.object import ObjectMeta

    return JAXJob(
        metadata=ObjectMeta(name="tiny", namespace="default"),
        spec=JAXJobSpec(
            replica_specs={
                "worker": ReplicaSpec(
                    replicas=2,
                    template=WorkloadSpec(entrypoint="noop", config={"steps": 2}),
                    resources=TPUResourceSpec(tpu_chips=1),
                )
            },
            parallelism=ParallelismSpec(data=2),
        ),
    )
