"""Cross-component name contracts, pinned against REAL components
(ISSUE 10): the autoscaler-probe ↔ engine-metrics series pair, the
centralized ``X-Kftpu-*`` header module riding through the chaos
middlebox, and the ``KFTPU_SANITIZE=contract`` runtime auditor agreeing
with the static extraction.

The probe pin is the load-bearing one: ``default_probe`` matches literal
series names against whatever a replica's ``/metrics`` renders, and
before this suite a rename on EITHER side broke nothing until the SLO
autoscaler silently held forever. Here the consumed set is derived from
the static contract extractor (not re-typed), so renaming the probe's
literals, the engine's definition sites, or ``_PROBE_SERIES`` each fail
a test."""

import json
import os
import urllib.request

import pytest
import jax

from kubeflow_tpu.analysis import core as analysis_core
from kubeflow_tpu.analysis import rules_contracts
from kubeflow_tpu.core.headers import (
    DEADLINE_HEADER, DECODE_ALTS_HEADER, DECODE_BACKEND_HEADER,
    FORWARD_HEADERS, HANDOFF_DTYPE_HEADER, HANDOFF_WIRE_HEADER, QOS_HEADER,
    TRACE_HEADER, USER_HEADER,
)
from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.obs.registry import parse_exposition
from kubeflow_tpu.runtime import sanitize
from kubeflow_tpu.serve.engine import LLMEngine
from kubeflow_tpu.serve.isvc_controller import _PROBE_SERIES, default_probe
from kubeflow_tpu.serve.server import ModelServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _probe_consumed_series() -> set:
    """The series names ``default_probe`` consumes, per the STATIC
    contract extractor over the real module — the same table ``kftpu
    lint`` X701 checks, so this test and the lint gate can never
    disagree about what the probe reads."""
    mod = analysis_core.load_module(
        os.path.join(REPO, "kubeflow_tpu", "serve", "isvc_controller.py"),
        "kubeflow_tpu/serve/isvc_controller.py")
    return {name for name, _ in
            rules_contracts._extract(mod)["series_consumed"]}


@pytest.fixture(scope="module")
def server():
    cfg = preset("tiny", vocab_size=512)
    params = init_decoder_params(jax.random.PRNGKey(0), cfg)
    engine = LLMEngine(
        cfg, BatchingSpec(max_batch_size=2, max_seq_len=96,
                          prefill_buckets=[32]),
        params=params)
    srv = ModelServer("contract-pin", engine, port=0)
    srv.start()
    # One real completed request so the latency percentiles (TTFT,
    # queue delay, per-QoS p95s) exist in the engine snapshot — the
    # contract covers the loaded-replica payload, not the idle one.
    body = json.dumps({"prompt": "pin", "max_tokens": 4,
                       "timeout": 30}).encode()
    req = urllib.request.Request(
        srv.url + "/v1/completions", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        r.read()
    yield srv
    srv.stop()


class TestAutoscalerSeriesContract:
    def test_extractor_chain_and_probe_tuple_agree(self):
        """The probe's match chain and its declared ``_PROBE_SERIES``
        must be the same set — a rename applied to one but not the other
        fails here before it can half-work at runtime."""
        assert _probe_consumed_series() == set(_PROBE_SERIES)

    def test_every_probed_series_is_in_a_real_metrics_payload(self, server):
        """Render a REAL engine /metrics payload and assert every series
        name ``default_probe`` matches on is present — fails if either
        the probe literals or the engine definition sites rename."""
        text = server.metrics_text()
        rendered = {name for name, _, _ in parse_exposition(text)}
        missing = _probe_consumed_series() - rendered
        assert not missing, (
            f"probe scrapes series the engine no longer renders: "
            f"{sorted(missing)}")

    def test_probe_parses_the_real_payload(self, server):
        """End to end over HTTP: the probe must come back ready with the
        latency signals populated from the real exposition payload."""
        got = default_probe(server.url, timeout=5.0)
        assert got is not None and got["ready"]
        assert got["requests_total"] >= 1
        assert got["ttft_p95_ms"] is not None
        assert got["queue_delay_p95_ms"] is not None
        assert got["qos_ttft_p95_ms"]       # default class is still a class


class TestHeaderModule:
    def test_one_owner_for_every_header(self):
        """The historical homes re-export the central constants — same
        objects, one spelling."""
        from kubeflow_tpu.obs import trace
        from kubeflow_tpu.serve import router

        assert trace.TRACE_HEADER is TRACE_HEADER
        assert router.DEADLINE_HEADER is DEADLINE_HEADER
        assert router.QOS_HEADER is QOS_HEADER
        assert USER_HEADER == "X-Kftpu-User"

    def test_forward_list_covers_the_serving_path(self):
        from kubeflow_tpu.core.headers import MODEL_HEADER

        assert set(FORWARD_HEADERS) == {
            DEADLINE_HEADER, QOS_HEADER, TRACE_HEADER,
            DECODE_BACKEND_HEADER, DECODE_ALTS_HEADER, MODEL_HEADER,
            HANDOFF_DTYPE_HEADER, HANDOFF_WIRE_HEADER}

    def test_chaos_proxy_forwards_the_whole_list(self):
        """The ChaosProxy's forward-list is DERIVED from core/headers —
        every serving-path header (trace included, which the old
        re-typed list dropped) rides through the middlebox."""
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from kubeflow_tpu.serve.faults import ChaosProxy

        seen: dict = {}

        class Echo(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                for h in FORWARD_HEADERS:
                    if self.headers.get(h):
                        seen[h] = self.headers[h]
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                data = b'{"ok": true}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Echo)
        httpd.daemon_threads = True
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        proxy = ChaosProxy(f"http://127.0.0.1:{httpd.server_address[1]}")
        proxy.start()
        try:
            req = urllib.request.Request(
                proxy.url + "/x", data=b"{}",
                headers={"Content-Type": "application/json",
                         DEADLINE_HEADER: "1000",
                         QOS_HEADER: "interactive",
                         DECODE_BACKEND_HEADER: "http://127.0.0.1:1",
                         DECODE_ALTS_HEADER: "http://127.0.0.1:2",
                         HANDOFF_DTYPE_HEADER: "int8",
                         HANDOFF_WIRE_HEADER: "2",
                         "X-Kftpu-Model": "tenant-a",
                         TRACE_HEADER: "ab" * 16 + "-" + "cd" * 8})
            with urllib.request.urlopen(req, timeout=10) as r:
                r.read()
        finally:
            proxy.stop()
            httpd.shutdown()
            httpd.server_close()
        assert set(seen) == set(FORWARD_HEADERS)


class TestRuntimeContractAuditor:
    def test_probe_scrape_records_consumed_series(self, server):
        """Under the auditor, a real probe scrape records exactly the
        statically-declared consumed set — the runtime half agreeing
        with the AST half."""
        sanitize.install_contract_auditor()
        try:
            sanitize.contract_auditor().reset()
            got = default_probe(server.url, timeout=5.0)
            assert got is not None
            report = sanitize.contract_report()
            consumed = set(report["series_consumed"])
            assert consumed
            assert consumed <= set(_PROBE_SERIES)
            # Rendering the scrape response also recorded the produced
            # side, and nothing runtime-observed is statically undeclared.
            assert set(report["series_produced"]) >= consumed
            doc = rules_contracts.contract_manifest(
                analysis_core.build_program(
                    [os.path.join(REPO, "kubeflow_tpu")], root=REPO))
            diff = sanitize.contract_diff(report, doc)
            assert diff["undeclared_series"] == []
            assert diff["undeclared_headers"] == []
        finally:
            sanitize.uninstall_contract_auditor()

    def test_auditor_off_is_free(self, server):
        sanitize.uninstall_contract_auditor()
        assert sanitize.contract_report() == {}
        got = default_probe(server.url, timeout=5.0)   # hooks are no-ops
        assert got is not None
