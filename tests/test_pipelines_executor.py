"""DAG-executor semantics: driver/launcher behavior (input resolution,
cache-skip, lineage), control flow (conditions, loops + fan-in, exit
handlers), failure propagation (SURVEY.md §2.5#40, §3.4)."""

from typing import NamedTuple

import pytest

from kubeflow_tpu.core.pipeline_specs import RunPhase
from kubeflow_tpu.pipelines import dsl, metadata as md
from kubeflow_tpu.pipelines.artifacts import ArtifactStore
from kubeflow_tpu.pipelines.compiler import compile_pipeline
from kubeflow_tpu.pipelines.executor import PipelineExecutor
from kubeflow_tpu.pipelines.metadata import MetadataStore

CALLS: list[str] = []


@dsl.component
def emit(n: int) -> list:
    CALLS.append("emit")
    return list(range(n))


@dsl.component
def total(data: list) -> int:
    CALLS.append("total")
    return sum(data)


@dsl.component
def double(x: int) -> int:
    CALLS.append("double")
    return 2 * x


@dsl.component
def merge(items: list) -> int:
    CALLS.append("merge")
    return sum(items)


@dsl.component
def boom(x: int) -> int:
    raise RuntimeError("kaput")


@dsl.component
def cleanup(tag: str = "t") -> str:
    CALLS.append("cleanup")
    return f"cleaned-{tag}"


@pytest.fixture()
def ex(tmp_path):
    CALLS.clear()
    return PipelineExecutor(ArtifactStore(str(tmp_path / "cas")),
                            MetadataStore(str(tmp_path / "md.db")))


class TestBasics:
    def test_linear_flow_and_outputs(self, ex):
        @dsl.pipeline
        def p(n: int = 3):
            t = total(data=emit(n=n))

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.SUCCEEDED
        assert res.tasks["total"].outputs["output"] == 3
        assert res.outputs == {"total.output": 3}

    def test_parameter_override_and_missing(self, ex):
        @dsl.pipeline
        def p(n: int = 3):
            emit(n=n)

        res = ex.run(compile_pipeline(p), {"n": 5}, run_name="r")
        assert res.tasks["emit"].outputs["output"] == [0, 1, 2, 3, 4]

        @dsl.pipeline
        def q(n: int):
            emit(n=n)

        with pytest.raises(ValueError, match="no default"):
            ex.run(compile_pipeline(q), run_name="r2")

    def test_dynamic_loop_from_task_output(self, ex):
        @dsl.pipeline
        def p(n: int = 3):
            data = emit(n=n)           # [0, 1, 2]
            with dsl.ParallelFor(data.output) as item:
                d = double(x=item)
            merge(items=d.output)

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.SUCCEEDED
        assert res.tasks["merge"].outputs["output"] == 6  # 0+2+4
        assert {n for n in res.tasks} >= {"double#0", "double#1", "double#2"}

    def test_empty_loop(self, ex):
        @dsl.pipeline
        def p():
            data = emit(n=0)
            with dsl.ParallelFor(data.output) as item:
                d = double(x=item)
            merge(items=d.output)

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.SUCCEEDED
        assert res.tasks["merge"].outputs["output"] == 0


class TestCaching:
    def test_cache_hit_and_arg_sensitivity(self, ex):
        @dsl.pipeline
        def p(n: int = 3):
            total(data=emit(n=n))

        ir = compile_pipeline(p)
        ex.run(ir, run_name="r1")
        assert CALLS == ["emit", "total"]
        res2 = ex.run(ir, run_name="r2")
        assert CALLS == ["emit", "total"]       # nothing re-ran
        assert res2.tasks["emit"].cached and res2.tasks["total"].cached
        assert res2.tasks["total"].outputs["output"] == 3
        ex.run(ir, {"n": 4}, run_name="r3")     # different args → re-run
        assert CALLS == ["emit", "total", "emit", "total"]

    def test_cache_disabled_per_run(self, ex):
        @dsl.pipeline
        def p():
            emit(n=2)

        ir = compile_pipeline(p)
        ex.run(ir, run_name="r1")
        ex.run(ir, run_name="r2", cache_enabled=False)
        assert CALLS == ["emit", "emit"]

    def test_cached_execution_recorded_in_lineage(self, ex):
        @dsl.pipeline
        def p():
            emit(n=2)

        ir = compile_pipeline(p)
        ex.run(ir, run_name="r1")
        res = ex.run(ir, run_name="r2")
        eid = res.tasks["emit"].execution_id
        info = ex.metadata.get_execution(eid)
        assert info["state"] == md.EXEC_CACHED
        assert info["properties"]["cached_from"] > 0


class TestFailure:
    def test_failure_skips_dependents_not_siblings(self, ex):
        @dsl.pipeline
        def p():
            b = boom(x=1)
            total(data=b.output)     # dependent: skipped
            emit(n=1)                # independent: runs

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.FAILED
        assert res.tasks["boom"].phase is RunPhase.FAILED
        assert "kaput" in res.tasks["boom"].error
        assert res.tasks["total"].skipped
        assert res.tasks["emit"].phase is RunPhase.SUCCEEDED

    def test_exit_handler_runs_on_failure(self, ex):
        @dsl.pipeline
        def p():
            c = cleanup()
            with dsl.ExitHandler(c):
                boom(x=1)

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.FAILED
        assert res.tasks["cleanup"].phase is RunPhase.SUCCEEDED
        assert "cleanup" in CALLS

    def test_failed_execution_recorded(self, ex):
        @dsl.pipeline
        def p():
            boom(x=1)

        res = ex.run(compile_pipeline(p), run_name="r")
        eid = res.tasks["boom"].execution_id
        assert ex.metadata.get_execution(eid)["state"] == md.EXEC_FAILED


class TestLineage:
    def test_full_provenance_graph(self, ex):
        @dsl.pipeline
        def p(n: int = 3):
            t = total(data=emit(n=n))

        res = ex.run(compile_pipeline(p), run_name="r")
        t_eid = res.tasks["total"].execution_id
        events = ex.metadata.events_by_execution(t_eid)
        inputs = [e for e in events if e[1] == md.EVENT_INPUT]
        outputs = [e for e in events if e[1] == md.EVENT_OUTPUT]
        assert len(inputs) == 1 and inputs[0][2] == "data"
        assert len(outputs) == 1 and outputs[0][2] == "output"
        # the input artifact is emit's output artifact
        e_eid = res.tasks["emit"].execution_id
        emit_out = [a for a, t, _ in ex.metadata.events_by_execution(e_eid)
                    if t == md.EVENT_OUTPUT]
        assert inputs[0][0] in emit_out
        lin = ex.metadata.lineage(outputs[0][0])
        assert set(lin["executions"]) == {e_eid, t_eid}
        # run context collects all executions
        assert set(ex.metadata.context_executions(res.context_id)) >= \
            {e_eid, t_eid}


class TestArtifacts:
    def test_cas_round_trip(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        uri = store.put_value({"a": [1, 2]})
        assert uri.startswith("cas://")
        assert store.get_value(uri) == {"a": [1, 2]}
        assert store.put_value({"a": [1, 2]}) == uri   # content-addressed
        obj = {1, 2, 3}  # not JSON-able → pickle codec
        assert store.get_value(store.put_value(obj)) == obj


@dsl.component
def pair_sum(a: int, b: int) -> int:
    CALLS.append("pair_sum")
    return a + b


class TestNestedParallelFor:
    """Nested ParallelFor (VERDICT r4 next #10, (U) KFP dsl.ParallelFor
    nesting): inner loops expand per outer instance with composite
    instance keys (m#i#j); fan-in outside both levels flattens."""

    def test_static_nested_fanout_and_flat_fanin(self, ex):
        @dsl.pipeline
        def p():
            with dsl.ParallelFor([1, 2]) as outer:
                with dsl.ParallelFor([10, 20, 30]) as inner:
                    s = pair_sum(a=outer, b=inner)
            merge(items=s.output)

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.SUCCEEDED
        # 2 x 3 instances with composite keys.
        keys = {n for n in res.tasks if n.startswith("pair_sum#")}
        assert keys == {f"pair_sum#{i}#{j}" for i in range(2)
                        for j in range(3)}
        # (1+10)+(1+20)+(1+30)+(2+10)+(2+20)+(2+30) = 129
        assert res.tasks["merge"].outputs["output"] == 129

    def test_inner_items_from_outer_element_field(self, ex):
        """The KFP idiom: iterate a field of each outer element."""
        @dsl.pipeline
        def p():
            groups = [{"base": 100, "xs": [1, 2]},
                      {"base": 200, "xs": [3]}]
            with dsl.ParallelFor(groups) as g:
                with dsl.ParallelFor(g["xs"]) as x:
                    s = pair_sum(a=g["base"], b=x)
            merge(items=s.output)

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.SUCCEEDED
        # Ragged inner lengths: 2 instances under outer#0, 1 under outer#1.
        assert res.tasks["merge"].outputs["output"] == (101 + 102) + 203

    def test_dynamic_outer_items_and_inner_chain(self, ex):
        """Outer items from a task output; a dependency chain inside the
        inner body keys both tasks per (i, j)."""
        @dsl.pipeline
        def p(n: int = 2):
            data = emit(n=n)               # [0, 1]
            with dsl.ParallelFor(data.output) as i:
                with dsl.ParallelFor([5, 7]) as j:
                    s = pair_sum(a=i, b=j)
                    d = double(x=s.output)
            merge(items=d.output)

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.SUCCEEDED
        # 2*((0+5)+(0+7)+(1+5)+(1+7)) = 2*26 = 52
        assert res.tasks["merge"].outputs["output"] == 52
        assert "double#0#1" in res.tasks
        # The inner chain wired instance-to-instance, not cross-product.
        assert res.tasks["double#1#0"].outputs["output"] == 2 * (1 + 5)

    def test_failure_in_one_inner_instance_skips_fanin(self, ex):
        @dsl.component
        def boom_if(x: int) -> int:
            if x == 7:
                raise RuntimeError("kaput")
            return x

        @dsl.pipeline
        def p():
            with dsl.ParallelFor([[1, 2], [7]]) as xs:
                with dsl.ParallelFor(xs) as x:
                    b = boom_if(x=x)
            merge(items=b.output)

        res = ex.run(compile_pipeline(p), run_name="r")
        assert res.phase is RunPhase.FAILED
        assert res.tasks["boom_if#1#0"].phase is RunPhase.FAILED
        assert res.tasks["merge"].skipped
