"""Metadata-store tests, run against BOTH backends (C++/ctypes native and
the pure-Python fallback) to pin identical semantics — the rebuild's analog
of ml-metadata's store tests ((U) google/ml-metadata metadata_store_test;
SURVEY.md §2.5#41)."""

import os
import threading

import pytest

from kubeflow_tpu.pipelines import metadata as md
from kubeflow_tpu.pipelines.metadata import MetadataStore, native_library

BACKENDS = ["python"] + (["native"] if native_library() is not None else [])


@pytest.fixture(params=BACKENDS)
def store(request, tmp_path):
    s = MetadataStore(str(tmp_path / "md.db"), backend=request.param)
    yield s
    s.close()


def test_native_backend_available():
    # The toolchain is in the image: the C++ store must build. This test
    # failing means the native component regressed to Python-only.
    assert native_library() is not None


class TestNodes:
    def test_artifact_round_trip(self, store):
        aid = store.create_artifact(
            "Dataset", uri="cas://abc",
            properties={"rows": 10, "split": 0.8, "name": "train"})
        art = store.get_artifact(aid)
        assert art["uri"] == "cas://abc"
        assert art["state"] == md.ART_PENDING
        assert art["properties"] == {"rows": 10, "split": 0.8, "name": "train"}
        store.update_artifact(aid, uri="cas://def", state=md.ART_LIVE,
                              properties={"rows": 12})
        art = store.get_artifact(aid)
        assert art["uri"] == "cas://def"
        assert art["state"] == md.ART_LIVE
        assert art["properties"]["rows"] == 12
        assert art["properties"]["name"] == "train"  # others kept

    def test_missing_nodes(self, store):
        assert store.get_artifact(999) is None
        assert store.get_execution(999) is None
        assert store.artifacts_of_type("nope") == []

    def test_types_deduplicate(self, store):
        a1 = store.create_artifact("Model")
        a2 = store.create_artifact("Model")
        assert store.artifacts_of_type("Model") == [a1, a2]
        # same name, different kind = different type
        e = store.create_execution("Model")
        assert store.executions_of_type("Model") == [e]

    def test_execution_state_machine(self, store):
        e = store.create_execution("train", properties={"cache_key": "k1"})
        assert store.get_execution(e)["state"] == md.EXEC_RUNNING
        store.update_execution(e, md.EXEC_COMPLETE)
        assert store.get_execution(e)["state"] == md.EXEC_COMPLETE
        assert store.find_executions_by_property("cache_key", "k1") == [e]
        assert store.find_executions_by_property("cache_key", "k2") == []


class TestLineage:
    def test_event_graph(self, store):
        raw = store.create_artifact("Dataset", uri="cas://raw")
        e1 = store.create_execution("preprocess")
        store.put_event(e1, raw, md.EVENT_INPUT, "raw")
        clean = store.create_artifact("Dataset", uri="cas://clean")
        store.put_event(e1, clean, md.EVENT_OUTPUT, "clean")
        e2 = store.create_execution("train")
        store.put_event(e2, clean, md.EVENT_INPUT, "data")
        model = store.create_artifact("Model", uri="cas://model")
        store.put_event(e2, model, md.EVENT_OUTPUT, "model")

        assert store.events_by_execution(e2) == [
            (clean, md.EVENT_INPUT, "data"), (model, md.EVENT_OUTPUT, "model")]
        assert store.events_by_artifact(clean) == [
            (e1, md.EVENT_OUTPUT), (e2, md.EVENT_INPUT)]
        lin = store.lineage(model)
        assert lin == {"artifacts": sorted([raw, clean, model]),
                       "executions": sorted([e1, e2])}
        # raw has no upstream
        assert store.lineage(raw) == {"artifacts": [raw], "executions": []}

    def test_contexts(self, store):
        ctx = store.create_context("pipeline_run", "demo/r1",
                                   properties={"pipeline": "demo"})
        e = store.create_execution("step")
        a = store.create_artifact("Artifact")
        store.add_association(ctx, e)
        store.add_attribution(ctx, a)
        store.add_association(ctx, e)  # idempotent
        assert store.context_executions(ctx) == [e]
        assert store.context_artifacts(ctx) == [a]
        # same (type, name) = same context
        assert store.create_context("pipeline_run", "demo/r1") == ctx


class TestConcurrency:
    def test_parallel_writers(self, store):
        ids: list[int] = []
        lock = threading.Lock()

        def writer(k):
            for i in range(20):
                aid = store.create_artifact("T", uri=f"cas://{k}/{i}",
                                            properties={"i": i})
                with lock:
                    ids.append(aid)

        threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(ids) == 80
        assert len(set(ids)) == 80
        assert len(store.artifacts_of_type("T")) == 80


class TestPersistence:
    def test_reopen(self, tmp_path, store):
        path = store.path
        aid = store.create_artifact("Dataset", uri="cas://x",
                                    properties={"n": 1})
        # Reopen with the *other* backend: on-disk format is shared.
        other = ("python" if store.backend == "native" else
                 ("native" if native_library() else "python"))
        with MetadataStore(path, backend=other) as again:
            art = again.get_artifact(aid)
            assert art["uri"] == "cas://x"
            assert art["properties"] == {"n": 1}


def test_large_id_list(tmp_path):
    # > the 256 first-guess buffer: exercises the grow-and-retry path.
    with MetadataStore(str(tmp_path / "big.db"),
                       backend=BACKENDS[-1]) as store:
        ids = [store.create_artifact("Bulk") for _ in range(300)]
        assert store.artifacts_of_type("Bulk") == ids


class TestObservations:
    """The dedicated observations table (katib observation_logs analog —
    SURVEY.md §2.4#33), identical across both backends."""

    def test_report_get_roundtrip(self, store):
        e = store.create_execution("tune_trial")
        store.report_observations(e, "loss", [(30, 3.0), (10, 1.0),
                                              (20, 2.0)])
        assert store.get_observations(e, "loss") == [(10, 1.0), (20, 2.0),
                                                     (30, 3.0)]
        assert store.get_observations(e, "nope") == []
        assert store.get_observations(e + 1, "loss") == []

    def test_upsert_per_step(self, store):
        e = store.create_execution("tune_trial")
        store.report_observations(e, "loss", [(5, 1.0)])
        store.report_observations(e, "loss", [(5, 0.5), (6, 0.4)])
        assert store.get_observations(e, "loss") == [(5, 0.5), (6, 0.4)]

    def test_metric_listing(self, store):
        e = store.create_execution("tune_trial")
        store.report_observations(e, "loss", [(1, 1.0)])
        store.report_observations(e, "accuracy", [(1, 0.1)])
        assert store.observation_metrics(e) == ["accuracy", "loss"]
        assert store.observation_metrics(e + 1) == []

    def test_hundred_thousand_points_read_under_a_second(self, store):
        """The scale that motivated the table: a 1e5-step log on one trial
        must write in batches and read back in <1s (the property packing
        crawled here)."""
        import time

        e = store.create_execution("tune_trial")
        pts = [(s, float(s) * 0.5) for s in range(100_000)]
        for i in range(0, len(pts), 10_000):
            store.report_observations(e, "loss", pts[i:i + 10_000])
        t0 = time.perf_counter()
        series = store.get_observations(e, "loss")
        dt = time.perf_counter() - t0
        assert len(series) == 100_000
        assert series[0] == (0, 0.0) and series[-1] == (99_999, 49_999.5)
        assert dt < 1.0, f"1e5-point read took {dt:.2f}s"


class TestNativeSanitizers:
    """Run the C++ store test under ASan/TSan — the reference's `go test
    -race` analog for the one native component (SURVEY.md §4.7, §5)."""

    @pytest.mark.parametrize("target", ["test-asan", "test-tsan"])
    def test_sanitized_build_passes(self, target, tmp_path):
        import shutil
        import subprocess

        if shutil.which("g++") is None:
            pytest.skip("no C++ toolchain")
        src = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "native", "metadata_store")
        res = subprocess.run(["make", target], cwd=src, capture_output=True,
                             text=True, timeout=300)
        assert res.returncode == 0, res.stdout + res.stderr
        assert "native test OK" in res.stdout + res.stderr
