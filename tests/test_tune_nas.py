"""NAS suggesters: DARTS (differentiable supernet relaxation) and ENAS
(weight-sharing controller + REINFORCE) — real search algorithms, not
"arch knobs are parameters" ((U) katib pkg/suggestion/v1beta1/nas/{darts,
enas}; SURVEY.md §2.4#34). The committed bar from the round-1 verdict:
beat random search on a fixed budget, and drive examples/nas_experiment.yaml
from a test."""

import os

import pytest

from kubeflow_tpu.core.tuning import FeasibleSpace, ParameterSpec
from kubeflow_tpu.tune.nas import DARTS, ENAS

SPECS = [
    ParameterSpec(name="mlp_dim", type="discrete",
                  feasible_space=FeasibleSpace(list=[32, 256])),
    ParameterSpec(name="hidden_act", type="categorical",
                  feasible_space=FeasibleSpace(list=["silu", "gelu"])),
    ParameterSpec(name="n_layers", type="int",
                  feasible_space=FeasibleSpace(min=1, max=3)),
    ParameterSpec(name="lr", type="double",
                  feasible_space=FeasibleSpace(min=0.001, max=0.01)),
]


def proxy_objective(assignment) -> float:
    """Deterministic stand-in for a trial's final loss, rewarding exactly
    the signal the searches can discover from data (model capacity — the
    synthetic LM stream is fit markedly better by the wide MLP branch). The
    search never sees this function — it trains its supernet on the stream —
    so doing well here demonstrates transfer, not leakage."""
    return 3.0 - 0.6 * (float(assignment["mlp_dim"]) >= 256)


@pytest.mark.slow
class TestDARTS:
    def test_search_discovers_capacity_and_caches(self):
        d = DARTS(SPECS, {"search_steps": 60, "random_state": 0})
        props, state = d.suggest(3, [], {})
        assert len(props) == 3
        # The supernet's mixture must favor the higher-capacity branch.
        assert props[0]["mlp_dim"] == 256
        assert state["proposals"]
        # Resume: a second call continues from cached proposals without
        # re-running the search (FromSuggestion semantics).
        more, state2 = d.suggest(2, [], state)
        assert len(more) == 2
        assert state2["cursor"] == state["cursor"] + 2
        import json

        json.dumps(state2)   # algorithm state must stay JSON-serializable

    def test_beats_random_on_fixed_budget(self):
        from kubeflow_tpu.tune.algorithms import RandomSearch

        budget = 3
        d = DARTS(SPECS, {"search_steps": 60, "random_state": 0})
        darts_props, _ = d.suggest(budget, [], {})
        r = RandomSearch(SPECS, {"random_state": 7})
        random_props, _ = r.suggest(budget, [], {})
        best_darts = min(proxy_objective(p) for p in darts_props)
        best_random = min(proxy_objective(p) for p in random_props)
        assert best_darts <= best_random
        # And strictly: DARTS's TOP-1 must already be optimal, and its
        # WHOLE budget beats random's average (no wasted trials on the
        # small branch).
        assert proxy_objective(darts_props[0]) <= best_random
        assert (sum(map(proxy_objective, darts_props)) / budget
                < sum(map(proxy_objective, random_props)) / budget)


@pytest.mark.slow
class TestENAS:
    def test_controller_discovers_capacity(self):
        e = ENAS(SPECS, {"search_rounds": 8, "random_state": 0})
        props, state = e.suggest(3, [], {})
        assert props[0]["mlp_dim"] == 256
        assert state["proposals"][0]["val_loss"] <= \
            state["proposals"][-1]["val_loss"]

    def test_beats_random_on_fixed_budget(self):
        from kubeflow_tpu.tune.algorithms import RandomSearch

        budget = 3
        e = ENAS(SPECS, {"search_rounds": 8, "random_state": 0})
        enas_props, _ = e.suggest(budget, [], {})
        r = RandomSearch(SPECS, {"random_state": 7})
        random_props, _ = r.suggest(budget, [], {})
        assert min(proxy_objective(p) for p in enas_props) <= \
            min(proxy_objective(p) for p in random_props)
        # Every ENAS trial lands on the discovered wide branch; random
        # wastes budget on the small one.
        assert (sum(map(proxy_objective, enas_props)) / budget
                < sum(map(proxy_objective, random_props)) / budget)


@pytest.mark.slow
def test_nas_experiment_yaml_end_to_end(tmp_path):
    """Drive examples/nas_experiment.yaml (swapped to the darts suggester)
    through the live control plane with real llm_pretrain trial processes —
    the committed NAS e2e the round-1 verdict called out as missing."""
    from kubeflow_tpu.core import load_manifests
    from kubeflow_tpu.operator.control_plane import (
        ControlPlane, ControlPlaneConfig,
    )
    from kubeflow_tpu.runtime.topology import Cluster, SliceTopology

    path = os.path.join(os.path.dirname(__file__), os.pardir, "examples",
                        "nas_experiment.yaml")
    (exp,) = load_manifests(path)
    exp.spec.algorithm.name = "darts"
    exp.spec.algorithm.settings = {"search_steps": 40, "random_state": 0}
    exp.spec.max_trial_count = 2
    exp.spec.parallel_trial_count = 2

    plane = ControlPlane(ControlPlaneConfig(
        base_dir=str(tmp_path),
        cluster=Cluster(slices=[SliceTopology(name="s0", generation="cpu",
                                              dims=(2, 2))]),
        platform="cpu"))
    plane.start()
    try:
        plane.submit(exp)
        done = plane.wait_for(exp, "Succeeded", timeout=300)
        assert done.status.trials_succeeded == 2
        opt = done.status.current_optimal_trial
        assert opt.trial_name and opt.objective_value is not None
        # DARTS proposals carry the searched arch knobs into the trials.
        assert "n_layers" in opt.parameter_assignments
        assert "mlp_dim" in opt.parameter_assignments
    finally:
        plane.stop()
