"""Request-lifecycle hardening: deadlines & cancellation (slot + paged-KV
reaping), bounded admission with load shedding, queue-delay budget, and the
stop()/stopped_clean contract (ISSUE 2 tentpole + satellites).

The engine fixture is module-scoped and manually stepped: lifecycle knobs
(max_queue, queue_delay_budget) are plain attributes mutated per test, so
one compiled engine serves every scenario."""

import threading
import time

import pytest
import jax

from kubeflow_tpu.core.serving import BatchingSpec
from kubeflow_tpu.models.config import preset
from kubeflow_tpu.models.decoder import init_decoder_params
from kubeflow_tpu.serve.engine import (
    EngineOverloaded, LLMEngine, SamplingParams,
)


@pytest.fixture(scope="module")
def cfg():
    return preset("tiny")


@pytest.fixture(scope="module")
def params(cfg):
    return init_decoder_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def engine(cfg, params):
    # Paged so every scenario also audits page-refcount balance; small
    # decode_steps so deadline reaping gets frequent scheduler control.
    return LLMEngine(
        cfg,
        BatchingSpec(max_batch_size=2, max_seq_len=64, prefill_buckets=[16],
                     paged=True, page_size=8, chunked_prefill_tokens=8,
                     decode_steps=4),
        params=params)


def _drain(engine, reqs=(), max_steps=500):
    for _ in range(max_steps):
        worked = engine.step()
        if worked == 0 and all(r.done.is_set() for r in reqs):
            return
    raise AssertionError("engine did not quiesce")


def test_cancel_frees_slot_and_pages_mid_flight(engine):
    req = engine.submit([1, 2, 3, 4, 5], SamplingParams(max_new_tokens=48))
    engine.step()                      # admit + a few decode steps
    assert not req.done.is_set()
    assert engine.kv_pages_in_use() > 0
    req.cancel()
    engine.step()                      # reaper runs first in step()
    assert req.done.is_set()
    assert req.finish_reason == "cancelled"
    assert engine.kv_pages_in_use() == 0, "cancel leaked KV pages"
    # The freed slot and pages serve the next request (acceptance: reuse).
    out = engine.generate([5, 6, 7], SamplingParams(max_new_tokens=4),
                          timeout=60)
    assert len(out) == 4
    assert engine.kv_pages_in_use() == 0
    assert engine.metrics.snapshot()["requests_cancelled"] >= 1


def test_deadline_reaps_live_slot_before_completion(engine):
    req = engine.submit([9, 8, 7], SamplingParams(max_new_tokens=48),
                        deadline=time.monotonic() + 0.05)
    engine.step()                      # admitted, decoding
    emitted_early = len(req.output_tokens)
    time.sleep(0.08)
    for _ in range(50):
        engine.step()
        if req.done.is_set():
            break
    assert req.finish_reason == "deadline"
    assert len(req.output_tokens) < 48, "deadline did not cut generation"
    assert emitted_early <= len(req.output_tokens)
    assert engine.kv_pages_in_use() == 0
    assert engine.metrics.snapshot()["requests_expired"] >= 1


def test_deadline_reaps_queued_request_without_decoding(engine):
    blockers = [engine.submit([i + 1] * 8, SamplingParams(max_new_tokens=24))
                for i in range(2)]     # occupy both slots
    engine.step()
    late = engine.submit([4, 4, 4], SamplingParams(max_new_tokens=4),
                         deadline=time.monotonic() + 0.02)
    time.sleep(0.05)
    engine.step()
    assert late.done.is_set()
    assert late.finish_reason == "deadline"
    assert late.output_tokens == []    # never touched the device
    _drain(engine, blockers)
    assert all(b.finish_reason in ("stop", "length") for b in blockers)
    assert engine.kv_pages_in_use() == 0


def test_bounded_admission_sheds_at_the_door(engine):
    engine.max_queue = 2
    try:
        # No stepping: everything parks in the admission queue.
        a = engine.submit([1, 2], SamplingParams(max_new_tokens=2))
        b = engine.submit([3, 4], SamplingParams(max_new_tokens=2))
        before = engine.metrics.snapshot()["requests_shed"]
        with pytest.raises(EngineOverloaded) as exc:
            engine.submit([5, 6], SamplingParams(max_new_tokens=2))
        assert exc.value.retry_after > 0
        assert engine.metrics.snapshot()["requests_shed"] == before + 1
    finally:
        engine.max_queue = 0
    _drain(engine, [a, b])
    assert engine.kv_pages_in_use() == 0


def test_queue_delay_budget_sheds_stale_requests(engine):
    engine.queue_delay_budget = 0.05
    try:
        blockers = [engine.submit([i + 1] * 8,
                                  SamplingParams(max_new_tokens=24))
                    for i in range(2)]
        engine.step()                  # both slots busy
        stale = engine.submit([7, 7], SamplingParams(max_new_tokens=2))
        time.sleep(0.08)
        engine.step()
        assert stale.done.is_set()
        assert stale.finish_reason == "shed"
        _drain(engine, blockers)
    finally:
        engine.queue_delay_budget = None
    assert engine.kv_pages_in_use() == 0


def test_overload_sheds_excess_but_keeps_capacity(engine):
    """Acceptance: offered load > capacity with a low bound -> excess shed
    with EngineOverloaded, admitted requests all complete (no collapse)."""
    engine.max_queue = 2
    admitted, shed = [], 0
    try:
        stop = threading.Event()

        def pump():
            while not stop.is_set():
                engine.step()
                time.sleep(0.001)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        for i in range(16):
            try:
                admitted.append(engine.submit(
                    [i % 50 + 1] * 4, SamplingParams(max_new_tokens=12)))
            except EngineOverloaded:
                shed += 1
            time.sleep(0.002)
        deadline = time.monotonic() + 60
        while not all(r.done.is_set() for r in admitted):
            assert time.monotonic() < deadline, "admitted requests hung"
            time.sleep(0.01)
        stop.set()
        t.join(timeout=5.0)
    finally:
        engine.max_queue = 0
    assert shed > 0, "offered load never tripped the bound"
    assert all(r.finish_reason in ("stop", "length") for r in admitted)
    _drain(engine, admitted)
    assert engine.kv_pages_in_use() == 0


def test_queue_delay_histogram_populated(engine):
    _, counts, _, n = engine.metrics.queue_delay_histogram()
    assert n > 0 and sum(counts) == n


def test_stop_clean_sets_flag(cfg, params):
    eng = LLMEngine(cfg, BatchingSpec(max_batch_size=1, max_seq_len=32,
                                      prefill_buckets=[16]), params=params)
    assert eng.stopped_clean is None
    eng.start()
    assert eng.stop() is True
    assert eng.stopped_clean is True


def test_stop_surfaces_wedged_thread(cfg, params):
    """Satellite: a join timeout must not be silent success — the leaked
    thread still holds device buffers."""
    eng = LLMEngine(cfg, BatchingSpec(max_batch_size=1, max_seq_len=32,
                                      prefill_buckets=[16]), params=params)
    release = threading.Event()
    eng._thread = threading.Thread(target=release.wait, daemon=True)
    eng._thread.start()
    assert eng.stop(timeout=0.1) is False
    assert eng.stopped_clean is False
    release.set()


def test_generate_timeout_cancels_orphan(engine):
    """Satellite: generate()'s TimeoutError must not orphan the request
    mid-engine — cancel() lets the scheduler free its slot and pages."""
    engine.start()
    try:
        # 2 ms: far below even a fully-warmed engine's 48-token run (the
        # pipelined hot loop finishes 48 tokens in ~17 ms on CPU — the old
        # 20 ms bound stopped timing out once decode stopped blocking on
        # per-round host fetches).
        with pytest.raises(TimeoutError):
            engine.generate([2] * 8, SamplingParams(max_new_tokens=48),
                            timeout=0.002)
        deadline = time.monotonic() + 10
        while engine.kv_pages_in_use() > 0:
            assert time.monotonic() < deadline, \
                "timed-out generate leaked its slot/pages"
            time.sleep(0.01)
    finally:
        assert engine.stop() is True
