"""Serving benchmark: continuous-batching req/s + TTFT/TPOT percentiles.

BASELINE config 2 evidence ("KServe req/s + p50 TTFT, v5e"); run by hand
(the driver's headline bench stays bench.py):

    python bench_serve.py [--workload uniform|mixed|prefix|all] [--paged]

Methodology (round-3 fix of round-2 weak #2 — numbers were
compile-confounded): every run WARMS the exact dispatch set first (the
workload's own request mix, 2× the slot count), then resets the clock and
measures steady state in two back-to-back segments, reporting both so the
run-to-run spread is visible in one process. Compile time never lands in
the measured window.

Workloads (closed-loop A/Bs; ``--workload scenarios`` is the open-loop
trace-driven path — see ``run_scenarios`` and kubeflow_tpu/loadgen/):
  uniform — fixed 512-token prompts, 64 new tokens (the round-1/2 shape).
  mixed   — lognormal prompt lengths 64..1024 at high concurrency under the
            SAME KV-pool HBM budget for both engines: the paged engine
            turns pool density into extra decode slots (48 vs 16), which is
            where paging should win throughput.
  prefix  — a shared 512-token system prompt + short unique tails: the
            paged prefix cache skips the shared prefill, which is where
            paging should win TTFT.
"""

from __future__ import annotations

import argparse
import json
import threading
import time


def _mk_engine(cfg, *, paged: bool, slots: int, buckets, max_pages=None,
               on_tpu: bool, adapters=()):
    from kubeflow_tpu.core.serving import BatchingSpec, LoRASpec
    from kubeflow_tpu.serve.engine import LLMEngine

    lora = (LoRASpec(max_adapters=max(4, min(len(adapters), 16)), rank=8)
            if adapters else LoRASpec())
    engine = LLMEngine(cfg, BatchingSpec(
        max_batch_size=slots, max_seq_len=cfg.max_seq_len,
        prefill_buckets=list(buckets),
        paged=paged, page_size=128, max_pages=max_pages,
        weights_dtype="bfloat16" if on_tpu else None, lora=lora))
    if adapters:
        import jax

        from kubeflow_tpu.serve.lora import AdapterSpec, init_adapter_weights

        for i, name in enumerate(adapters):
            engine._lora.register(AdapterSpec(
                name, rank=8,
                weights=init_adapter_weights(jax.random.PRNGKey(100 + i),
                                             cfg, 8)))
    return engine


def _drive(engine, prompts, params, concurrency):
    """Closed-loop client pool over a fixed prompt list. Returns
    (wall, results[(ttft, total, tokens)])."""
    results = []
    lock = threading.Lock()
    it = iter(prompts)
    it_lock = threading.Lock()

    def client():
        while True:
            with it_lock:
                prompt = next(it, None)
            if prompt is None:
                return
            t0 = time.perf_counter()
            req = engine.submit(list(prompt), params)
            first = None
            tokens = 0
            while True:
                tok = req.stream.get()
                if tok is None:
                    break
                tokens += 1
                if first is None:
                    first = time.perf_counter() - t0
            with lock:
                results.append((first, time.perf_counter() - t0, tokens))

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client)
               for _ in range(max(1, concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600.0)   # generous: clients exit once their requests drain
    return time.perf_counter() - t_start, results


def _summarize(wall, results):
    # Quantiles via the shared obs/stats implementation (ISSUE 11): the
    # same linear-interpolation statistic EngineMetrics and the loadgen
    # report, so client-side and engine-side percentiles are comparable.
    from kubeflow_tpu.obs.stats import quantile

    ttfts = sorted(r[0] for r in results if r[0] is not None)
    tokens = sum(r[2] for r in results)
    return {
        "req_s": round(len(results) / wall, 2),
        "p50_ttft_ms": round(quantile(ttfts, 0.5) * 1e3, 1),
        "p99_ttft_ms": round(quantile(ttfts, 0.99) * 1e3, 1),
        "decode_tok_s": round(tokens / wall, 1),
    }


def _measure(engine, make_prompts, params, concurrency, requests,
             warm_prompts):
    """The shared A/B measurement protocol (every workload uses this — a
    methodology fix lands once): warm the exact dispatch set, reset the
    clock, measure two back-to-back segments, report both + spread."""
    from kubeflow_tpu.serve.engine import EngineMetrics

    engine.start()
    _drive(engine, warm_prompts, params, concurrency)
    engine.metrics = EngineMetrics()
    segs = []
    for _ in range(2):
        wall, results = _drive(engine, make_prompts(requests), params,
                               concurrency)
        segs.append(_summarize(wall, results))
    engine.stop()
    vals = [s["req_s"] for s in segs]
    return {
        "value": round(sum(vals) / len(vals), 2),
        "segments": segs,
        "spread_pct": round(
            100 * abs(vals[0] - vals[1]) / max(max(vals), 1e-9), 1),
        # Engine-side counters for the measured segments only (the warmup
        # ran against a throwaway EngineMetrics) — the spec A/B reads
        # acceptance rate / verified tokens per step from here.
        "engine_metrics": engine.metrics.snapshot(),
    }


def _prompts_for(workload, n, cfg, prompt_len, rng, max_new):
    # Generated prompts must leave room for generation: cap at
    # max_seq_len - max_new - 1 (the tiny CPU config's 128 would otherwise
    # reject every mixed/prefix prompt at submit).
    cap = cfg.max_seq_len - max_new - 1
    prompt_len = min(prompt_len, cap)
    if workload == "uniform":
        return [rng.integers(1, cfg.vocab_size, size=prompt_len).tolist()
                for _ in range(n)]
    if workload == "mixed":
        lens = np.clip((rng.lognormal(5.3, 0.8, size=n)).astype(int),
                       min(64, cap), min(1024, cap))
        return [rng.integers(1, cfg.vocab_size, size=int(l)).tolist()
                for l in lens]
    if workload == "prefix":
        tail = min(64, max(1, cap // 4))
        system = rng.integers(1, cfg.vocab_size,
                              size=min(prompt_len, cap - tail)).tolist()
        return [system + rng.integers(1, cfg.vocab_size, size=tail).tolist()
                for _ in range(n)]
    raise ValueError(workload)


import numpy as np  # noqa: E402  (used by _prompts_for)


def run_bench(workload: str, requests: int, concurrency: int,
              prompt_len: int, max_new: int, paged: bool = False) -> dict:
    import jax

    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import SamplingParams

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = preset(
            "llama3-8b",
            n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
            mlp_dim=8192, vocab_size=32000, max_seq_len=2048)
        model_tag = "llama3-0.6b"
    else:
        cfg = preset("tiny")
        model_tag = "tiny"
        prompt_len = min(prompt_len, 64)

    # KV HBM budget: 16 contiguous slots × max_seq_len. The paged engine
    # gets the SAME pool (16×2048/128 = 256 pages) but may run more slots —
    # pool density is the whole point of paging on mixed traffic.
    cap = cfg.max_seq_len - max_new - 1
    prompt_len = min(prompt_len, cap)
    base_slots = min(16, concurrency)
    pool_pages = base_slots * cfg.max_seq_len // 128
    if workload == "mixed":
        buckets = sorted({min(b, cfg.max_seq_len) for b in
                          (128, 256, 512, 1024)})
        # Density comparison needs offered load above the contiguous slot
        # count: the paged engine runs 3× the slots over the SAME pool, and
        # both engines face the same concurrency.
        concurrency = max(concurrency, 2 * base_slots)
        slots = 3 * base_slots if paged else base_slots
    elif workload == "prefix":
        buckets = [min(prompt_len + 128, cfg.max_seq_len)]
        slots = base_slots
    else:
        buckets = [prompt_len]
        slots = base_slots
    engine = _mk_engine(cfg, paged=paged, slots=slots, buckets=buckets,
                        max_pages=pool_pages if paged else None,
                        on_tpu=on_tpu)
    params = SamplingParams(max_new_tokens=max_new, temperature=0.0)
    rng = np.random.default_rng(0)

    # Warm the EXACT dispatch set: one prompt per configured prefill bucket
    # (deterministic — a rare bucket must not compile mid-measurement) plus
    # 2× slots of the workload's own mix.
    warm = [rng.integers(1, cfg.vocab_size,
                         size=max(1, min(b - 1, cap))).tolist()
            for b in buckets]
    warm += _prompts_for(workload, 2 * slots, cfg, prompt_len, rng, max_new)
    m = _measure(engine,
                 lambda n: _prompts_for(workload, n, cfg, prompt_len, rng,
                                        max_new),
                 params, concurrency, requests, warm)
    return {
        "metric": f"serve_req_per_sec[{model_tag},{workload},"
                  f"gen{max_new},c{concurrency}"
                  f"{',paged' if paged else ''}]",
        "value": m["value"],
        "unit": "req/s",
        "vs_baseline": 1.0,
        "detail": {
            "segments": m["segments"],
            "spread_pct": m["spread_pct"],
            "slots": slots,
            "concurrency": concurrency,
            "pool_pages": pool_pages if paged else None,
            "requests_per_segment": requests,
        },
    }


def run_moe_ab(requests: int, concurrency: int, prompt_len: int,
               max_new: int, only: str = "all") -> list[dict]:
    """Mixtral-0.8b served A/B (VERDICT r3 #3): dense oracle vs the
    dispatch prefill (k/E of dense MLP FLOPs on the TTFT-dominating pass)
    vs zero-drop dispatch decode — same engine pool, same warmed two-
    segment methodology. Prefill-heavy workload (long prompts, short
    generations) so the prefill impl is what the req/s measures."""
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import (
        LLMEngine, SamplingParams,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = preset(
            "mixtral-8x7b",
            n_layers=8, hidden=1024, n_heads=16, n_kv_heads=4, head_dim=64,
            mlp_dim=3584, vocab_size=32000, max_seq_len=2048)
        model_tag = "mixtral-0.8b-8e-top2"
    else:
        cfg = preset("tiny-moe")
        model_tag = "tiny-moe"
        prompt_len = min(prompt_len, 64)
    cap = cfg.max_seq_len - max_new - 1
    prompt_len = min(prompt_len, cap)
    slots = min(16, concurrency)
    rng = np.random.default_rng(0)
    params = SamplingParams(max_new_tokens=max_new, temperature=0.0)

    variants = [
        ("dense", {"moe_prefill_impl": "dense", "moe_decode_impl": "dense"}),
        ("dispatch_prefill", {"moe_prefill_impl": "dispatch",
                              "moe_decode_impl": "dense"}),
        ("dispatch_prefill+zd_decode", {"moe_prefill_impl": "dispatch",
                                        "moe_decode_impl": "zero_drop"}),
    ]
    if only != "all":
        variants = [vk for vk in variants if vk[0] == only]
    rows = []
    for tag, knobs in variants:
        engine = LLMEngine(cfg, BatchingSpec(
            max_batch_size=slots, max_seq_len=cfg.max_seq_len,
            prefill_buckets=[prompt_len],
            weights_dtype="bfloat16" if on_tpu else None, **knobs))
        gen = lambda n: [rng.integers(1, cfg.vocab_size,          # noqa: E731
                                      size=prompt_len).tolist()
                         for _ in range(n)]
        m = _measure(engine, gen, params, concurrency, requests,
                     warm_prompts=gen(2 * slots))
        rows.append({
            "metric": f"serve_moe_req_per_sec[{model_tag},{tag},"
                      f"p{prompt_len},gen{max_new},c{concurrency}]",
            "value": m["value"],
            "unit": "req/s",
            "vs_baseline": 1.0,
            "detail": {"segments": m["segments"],
                       "spread_pct": m["spread_pct"],
                       "slots": slots,
                       "requests_per_segment": requests},
        })
    return rows


def run_quant_ab(requests: int, concurrency: int, prompt_len: int,
                 max_new: int, only: str = "all") -> list[dict]:
    """int8 weight-only + int8-KV served A/B (VERDICT r4 #3): bf16 vs
    quantized weights (contiguous engine — isolates the decode param-read
    halving) and paged bf16 vs paged int8 KV at the SAME pool page count
    (isolates the read-traffic change; the density win — 2x resident
    tokens/byte — is architectural, AOT-proven in BASELINE.md).
    Decode-heavy workload (short prompts, long generations) so the per-step
    param/KV read is what the req/s measures."""
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import (
        LLMEngine, SamplingParams,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        # Bigger than the 0.6b serving config: decode is param-read-bound,
        # so the thing int8 halves should dominate the step.
        cfg = preset(
            "llama3-8b",
            n_layers=16, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
            mlp_dim=8192, vocab_size=32000, max_seq_len=2048)
        model_tag = "llama3-1.2b"
        # Enforce the decode-heavy shape the metric name claims: short
        # prompts, long generations (the CLI defaults are prefill-leaning).
        prompt_len = min(prompt_len, 128)
        max_new = max(max_new, 128)
    else:
        cfg = preset("tiny")
        model_tag = "tiny"
        prompt_len = min(prompt_len, 64)
    cap = cfg.max_seq_len - max_new - 1
    prompt_len = min(prompt_len, cap)
    slots = min(16, concurrency)
    rng = np.random.default_rng(0)
    params = SamplingParams(max_new_tokens=max_new, temperature=0.0)
    pool_pages = slots * cfg.max_seq_len // 128

    variants = [
        ("bf16", {}),
        ("int8w", {"quantize": "int8"}),
        ("paged_bf16", {"paged": True, "max_pages": pool_pages,
                        "paged_attn_impl": "gather"}),
        ("paged_int8kv", {"paged": True, "max_pages": pool_pages,
                          "quantize": "int8", "kv_cache_dtype": "int8",
                          "paged_attn_impl": "gather"}),
    ]
    if only != "all":
        variants = [vk for vk in variants if vk[0] == only]
    rows = []
    for tag, knobs in variants:
        engine = LLMEngine(cfg, BatchingSpec(
            max_batch_size=slots, max_seq_len=cfg.max_seq_len,
            prefill_buckets=[prompt_len], chunked_prefill_tokens=512,
            weights_dtype="bfloat16" if on_tpu else None, **knobs))
        gen = lambda n: [rng.integers(1, cfg.vocab_size,          # noqa: E731
                                      size=prompt_len).tolist()
                         for _ in range(n)]
        m = _measure(engine, gen, params, concurrency, requests,
                     warm_prompts=gen(2 * slots))
        rows.append({
            "metric": f"serve_quant_req_per_sec[{model_tag},{tag},"
                      f"p{prompt_len},gen{max_new},c{concurrency}]",
            "value": m["value"],
            "unit": "req/s",
            "vs_baseline": 1.0,
            "detail": {"segments": m["segments"],
                       "spread_pct": m["spread_pct"],
                       "slots": slots,
                       "requests_per_segment": requests},
        })
    return rows


def run_longctx_ab(requests: int, concurrency: int, prompt_len: int,
                   max_new: int, only: str = "all") -> list[dict]:
    """Long-context serving (VERDICT r4 next #4 — the paged kernel's home
    turf): S>=4k contexts (long prompts, long decode residency), A/B
    paged-gather vs the Pallas paged-attention kernel on the SAME pool.
    This is the measurement behind round-2's 'the saving scales with
    context length and slot count' claim — at 256-768-token contexts the
    kernel measured +9.5%; here the per-step gather materializes 4k+ of KV
    per slot, which the direct-page-read kernel never does."""
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import (
        LLMEngine, SamplingParams,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = preset(
            "llama3-8b",
            n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
            mlp_dim=8192, vocab_size=32000, max_seq_len=8192)
        model_tag = "llama3-0.6b-s8k"
        prompt_len = max(prompt_len, 4096)
    else:
        cfg = preset("tiny")
        model_tag = "tiny"
        prompt_len = min(prompt_len, 64)
    cap = cfg.max_seq_len - max_new - 1
    prompt_len = min(prompt_len, cap)
    slots = min(8, concurrency)          # 8 slots x 8k KV ≈ 1 GB at 0.6b
    pool_pages = slots * cfg.max_seq_len // 128
    rng = np.random.default_rng(0)
    params = SamplingParams(max_new_tokens=max_new, temperature=0.0)

    variants = [
        ("paged_gather", {"paged_attn_impl": "gather"}),
        ("paged_pallas", {"paged_attn_impl": "pallas"}),
    ]
    if only != "all":
        variants = [vk for vk in variants if vk[0] == only]
    rows = []
    for tag, knobs in variants:
        if tag == "paged_pallas" and not on_tpu:
            continue                     # Mosaic kernel needs the chip
        engine = LLMEngine(cfg, BatchingSpec(
            max_batch_size=slots, max_seq_len=cfg.max_seq_len,
            paged=True, page_size=128, max_pages=pool_pages,
            chunked_prefill_tokens=1024, max_concurrent_prefills=2,
            weights_dtype="bfloat16" if on_tpu else None, **knobs))
        gen = lambda n: [rng.integers(1, cfg.vocab_size,          # noqa: E731
                                      size=prompt_len).tolist()
                         for _ in range(n)]
        m = _measure(engine, gen, params, concurrency, requests,
                     warm_prompts=gen(max(4, slots)))
        rows.append({
            "metric": f"serve_longctx_req_per_sec[{model_tag},{tag},"
                      f"p{prompt_len},gen{max_new},c{concurrency}]",
            "value": m["value"],
            "unit": "req/s",
            "vs_baseline": 1.0,
            "detail": {"segments": m["segments"],
                       "spread_pct": m["spread_pct"],
                       "slots": slots, "pool_pages": pool_pages,
                       "requests_per_segment": requests},
        })
    return rows


def run_spec_ab(requests: int, concurrency: int, prompt_len: int,
                max_new: int, only: str = "all", paged: bool = False,
                spec_k: int = 6) -> list[dict]:
    """Speculative decoding served A/B: spec-off vs n-gram-draft spec-on at
    a DECODE-HEAVY shape (short templated prompts, long generations — the
    dispatch/HBM-bound regime speculation attacks). The workload's prompts
    are a repeated template ("templated suffix": extraction, code, JSON —
    the traffic class lookup drafting targets), so the drafter proposes
    from the first decode round; greedy continuations additionally
    self-repeat, which is the same property in the generated stream.
    Reports decode tok/s per variant + acceptance/verified-tokens-per-step
    from the engine, and a final speedup row (the headline)."""
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec, SpeculativeSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = preset(
            "llama3-8b",
            n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
            mlp_dim=8192, vocab_size=32000, max_seq_len=2048)
        model_tag = "llama3-0.6b"
        max_new = max(max_new, 512)          # the decode-heavy gen512 shape
        prompt_len = min(prompt_len, 256)
    else:
        cfg = preset("tiny", max_seq_len=1024)
        model_tag = "tiny-s1k"
        prompt_len = min(prompt_len, 64)
        max_new = min(max(max_new, 256), 512)
    cap = cfg.max_seq_len - max_new - 1
    prompt_len = min(prompt_len, cap)
    slots = min(16, concurrency)
    rng = np.random.default_rng(0)
    params = SamplingParams(max_new_tokens=max_new, temperature=0.0)

    unit = rng.integers(1, cfg.vocab_size, size=16).tolist()

    def gen(n):
        # Templated suffix: a shared repeating unit with a per-request
        # random head — the n-gram drafter locks onto the repetition, the
        # unique head keeps requests distinct (no prefix-cache confound).
        out = []
        for _ in range(n):
            head = rng.integers(1, cfg.vocab_size, size=8).tolist()
            reps = unit * (max(prompt_len - len(head), 1) // len(unit) + 1)
            out.append((head + reps)[:prompt_len])
        return out

    variants = [
        ("spec_off", SpeculativeSpec(mode="off")),
        ("spec_ngram", SpeculativeSpec(mode="ngram", k=spec_k)),
    ]
    if only != "all":
        variants = [vk for vk in variants if vk[0] == only]
    rows = []
    toks = {}
    for tag, spec in variants:
        engine = LLMEngine(cfg, BatchingSpec(
            max_batch_size=slots, max_seq_len=cfg.max_seq_len,
            prefill_buckets=[max(prompt_len, 16)],
            paged=paged, page_size=128,
            weights_dtype="bfloat16" if on_tpu else None,
            speculative=spec))
        m = _measure(engine, gen, params, concurrency, requests,
                     warm_prompts=gen(max(4, slots)))
        tok_s = [s["decode_tok_s"] for s in m["segments"]]
        toks[tag] = sum(tok_s) / len(tok_s)
        em = m["engine_metrics"]
        rows.append({
            "metric": f"serve_spec_decode_tok_s[{model_tag},{tag},"
                      f"p{prompt_len},gen{max_new},c{concurrency},"
                      f"k{spec_k}{',paged' if paged else ''}]",
            "value": round(toks[tag], 1),
            "unit": "tok/s",
            "vs_baseline": 1.0,
            "detail": {
                "segments": m["segments"],
                "spread_pct": m["spread_pct"],
                "req_s": m["value"],
                "slots": slots,
                "requests_per_segment": requests,
                "spec_acceptance_rate": round(
                    em.get("spec_acceptance_rate", 0.0), 4),
                "spec_tokens_per_step": round(
                    em.get("spec_tokens_per_step", 0.0), 3),
                "spec_draft_overhead": round(
                    em.get("spec_draft_overhead", 0.0), 4),
                "spec_rounds": em.get("spec_rounds", 0),
            },
        })
    if len(toks) == 2:
        rows.append({
            "metric": f"serve_spec_speedup[{model_tag},ngram_vs_off,"
                      f"p{prompt_len},gen{max_new},c{concurrency},"
                      f"k{spec_k}{',paged' if paged else ''}]",
            "value": round(toks["spec_ngram"] / max(toks["spec_off"], 1e-9),
                           3),
            "unit": "x decode tok/s",
            "vs_baseline": 1.0,
            "detail": {"spec_on_tok_s": round(toks["spec_ngram"], 1),
                       "spec_off_tok_s": round(toks["spec_off"], 1)},
        })
    return rows


def run_hotloop_ab(requests: int, concurrency: int, prompt_len: int,
                   max_new: int, only: str = "all",
                   paged: bool = False) -> list[dict]:
    """Decode hot-loop host-overhead A/B (ISSUE 4 tentpole): pipelined
    dispatch + device-resident scheduler state ON vs the synchronous
    dispatch-then-consume loop, same engine shape, same process, warmed
    two-segment methodology. Decode-heavy greedy workload (short prompts,
    long generations) so per-round host overhead is what the tok/s
    measures. Reports decode tok/s per variant, host-gap p50/p99 and
    dispatch depth from the engine's own counters, and a speedup row.
    Steady-state rounds upload zero full scheduler-state arrays either
    way (the device-resident half is unconditional — the A/B isolates
    the pipelining half)."""
    import jax

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = preset(
            "llama3-8b",
            n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
            mlp_dim=8192, vocab_size=32000, max_seq_len=2048)
        model_tag = "llama3-0.6b"
        max_new = max(max_new, 256)          # decode-heavy
        prompt_len = min(prompt_len, 128)
    else:
        cfg = preset("tiny", max_seq_len=1024)
        model_tag = "tiny-s1k"
        prompt_len = min(prompt_len, 64)
        max_new = min(max(max_new, 128), 512)
    cap = cfg.max_seq_len - max_new - 1
    prompt_len = min(prompt_len, cap)
    slots = min(16, concurrency)
    rng = np.random.default_rng(0)
    params = SamplingParams(max_new_tokens=max_new, temperature=0.0)

    def gen(n):
        return [rng.integers(1, cfg.vocab_size, size=prompt_len).tolist()
                for _ in range(n)]

    variants = [("pipelined_off", False), ("pipelined_on", True)]
    if only != "all":
        variants = [vk for vk in variants if vk[0] == only]
    rows = []
    toks = {}
    for tag, pipelined in variants:
        engine = LLMEngine(cfg, BatchingSpec(
            max_batch_size=slots, max_seq_len=cfg.max_seq_len,
            prefill_buckets=[max(prompt_len, 16)],
            paged=paged, page_size=128,
            weights_dtype="bfloat16" if on_tpu else None,
            pipelined_decode=pipelined))
        m = _measure(engine, gen, params, concurrency, requests,
                     warm_prompts=gen(max(4, slots)))
        tok_s = [s["decode_tok_s"] for s in m["segments"]]
        toks[tag] = sum(tok_s) / len(tok_s)
        em = m["engine_metrics"]
        rows.append({
            "metric": f"serve_hotloop_decode_tok_s[{model_tag},{tag},"
                      f"p{prompt_len},gen{max_new},c{concurrency}"
                      f"{',paged' if paged else ''}]",
            "value": round(toks[tag], 1),
            "unit": "tok/s",
            "vs_baseline": 1.0,
            "detail": {
                "segments": m["segments"],
                "spread_pct": m["spread_pct"],
                "req_s": m["value"],
                "slots": slots,
                "requests_per_segment": requests,
                "host_gap_p50_ms": round(em.get("host_gap_p50_ms", 0.0), 3),
                "host_gap_p99_ms": round(em.get("host_gap_p99_ms", 0.0), 3),
                "host_gap_total_s": round(em.get("host_gap_seconds", 0.0),
                                          3),
                "dispatch_depth": em.get("dispatch_depth", 0),
                "state_uploads": dict(engine._dstate.stats),
                "decode_rounds": engine.decode_rounds,
            },
        })
    if len(toks) == 2:
        rows.append({
            "metric": f"serve_hotloop_speedup[{model_tag},pipelined_vs_off,"
                      f"p{prompt_len},gen{max_new},c{concurrency}"
                      f"{',paged' if paged else ''}]",
            "value": round(
                toks["pipelined_on"] / max(toks["pipelined_off"], 1e-9), 3),
            "unit": "x decode tok/s",
            "vs_baseline": 1.0,
            "detail": {"on_tok_s": round(toks["pipelined_on"], 1),
                       "off_tok_s": round(toks["pipelined_off"], 1)},
        })
    return rows


def run_scenarios(requests: int, rate_rps: float, prompt_len: int,
                  max_new: int, paged: bool = False,
                  only: str = "all") -> list[dict]:
    """Open-loop trace-driven scenario matrix (ISSUE 11): replay the
    canonical loadgen scenarios (uniform Poisson / bursty multi-QoS /
    shared-prefix long-tail) against one engine and report the full
    attribution join — client req/s + TTFT/TPOT percentiles + goodput
    under SLO, engine-internal /metrics signals, and per-phase
    (queued/prefill/decode) span breakdowns. Unlike the closed-loop
    workloads above, the offered rate here is a fixed property of the
    scenario, so queueing collapse shows up as latency/goodput rows
    instead of silently throttling the client pool."""
    import jax

    from kubeflow_tpu.loadgen import (
        EngineTarget, build_report, run_scenario, standard_matrix,
    )
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.obs.trace import get_tracer
    from kubeflow_tpu.serve.server import serving_metrics_registry

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = preset(
            "llama3-8b",
            n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
            mlp_dim=8192, vocab_size=32000, max_seq_len=2048)
        model_tag = "llama3-0.6b"
    else:
        cfg = preset("tiny")
        model_tag = "tiny"
        prompt_len = min(prompt_len, 48)
    cap = cfg.max_seq_len - max_new - 1
    prompt_len = min(prompt_len, max(cap // 2, 8))
    scenarios = standard_matrix(num_requests=requests, rate_rps=rate_rps,
                                prompt_len=prompt_len, max_new=max_new)
    if only != "all":
        scenarios = [s for s in scenarios if s.name == only]
        if not scenarios:
            raise SystemExit(f"unknown scenario {only!r}")
    tracer = get_tracer()
    rows = []
    for sc in scenarios:
        slots = 16
        buckets = sorted({min(_p2(prompt_len), cap), min(2 * prompt_len, cap)})
        engine = _mk_engine(cfg, paged=paged, slots=slots, buckets=buckets,
                            max_pages=(slots * cfg.max_seq_len // 128
                                       if paged else None), on_tpu=on_tpu,
                            adapters=sc.adapter_ids)
        engine.start()
        try:
            tracer.reset()
            # Warm segment compiles the dispatch set, then the measured
            # replay runs on a reset metrics window (the two-segment
            # protocol lives in scripts/serve_perf_smoke.py; this is the
            # by-hand bench surface).
            from kubeflow_tpu.serve.engine import EngineMetrics
            run_scenario(EngineTarget(engine), sc, vocab_size=cfg.vocab_size,
                         max_prompt_len=cap - 1, tracer=tracer)
            engine.metrics = EngineMetrics()
            tracer.reset()
            run = run_scenario(EngineTarget(engine), sc,
                               vocab_size=cfg.vocab_size,
                               max_prompt_len=cap - 1, tracer=tracer)
            text = serving_metrics_registry([("bench", engine)]).render()
            rep = build_report(run, metrics_text=text, tracer=tracer)
        finally:
            engine.stop()
        rows.append({
            "metric": f"serve_scenario_req_per_sec[{model_tag},{sc.name},"
                      f"r{rate_rps:g},n{requests}"
                      f"{',paged' if paged else ''}]",
            "value": rep["req_s"],
            "unit": "req/s",
            "vs_baseline": 1.0,
            "detail": rep,
        })
    return rows


def _p2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="uniform",
                    choices=["uniform", "mixed", "prefix", "all", "moe",
                             "quant", "longctx", "spec", "hotloop",
                             "scenarios"])
    ap.add_argument("--requests", type=int, default=48,
                    help="per measured segment (two segments run)")
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=512)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--paged", action="store_true",
                    help="paged KV cache + prefix caching engine")
    ap.add_argument("--moe-variant", default="all",
                    choices=["all", "dense", "dispatch_prefill",
                             "dispatch_prefill+zd_decode"],
                    help="moe workload: run one variant per process to fit "
                         "tunnel-compile time budgets (cross-process "
                         "comparisons carry session noise — prefer one "
                         "process for the A/B)")
    ap.add_argument("--variant", default="all",
                    choices=["all", "dense", "dispatch_prefill",
                             "dispatch_prefill+zd_decode", "bf16", "int8w",
                             "paged_bf16", "paged_int8kv", "paged_gather",
                             "paged_pallas", "spec_off", "spec_ngram",
                             "pipelined_off", "pipelined_on"],
                    help="moe/quant/longctx/spec/hotloop workloads: run "
                         "one variant")
    ap.add_argument("--spec-k", type=int, default=6,
                    help="spec workload: draft tokens per round")
    ap.add_argument("--rate", type=float, default=8.0,
                    help="scenarios workload: offered open-loop req/s")
    ap.add_argument("--scenario", default="all",
                    choices=["all", "uniform", "bursty_qos",
                             "shared_prefix"],
                    help="scenarios workload: run one scenario")
    args = ap.parse_args()
    if args.workload == "scenarios":
        rows = run_scenarios(args.requests, args.rate, args.prompt_len,
                             args.max_new, paged=args.paged,
                             only=args.scenario)
        for row in rows:
            print(json.dumps(row), flush=True)
        raise SystemExit(0)
    if args.workload == "hotloop":
        rows = run_hotloop_ab(args.requests, args.concurrency,
                              args.prompt_len, args.max_new,
                              only=args.variant, paged=args.paged)
        for row in rows:
            print(json.dumps(row), flush=True)
        raise SystemExit(0)
    if args.workload == "spec":
        rows = run_spec_ab(args.requests, args.concurrency, args.prompt_len,
                           args.max_new, only=args.variant,
                           paged=args.paged, spec_k=args.spec_k)
        for row in rows:
            print(json.dumps(row), flush=True)
        raise SystemExit(0)
    if args.workload == "moe":
        only = args.variant if args.variant != "all" else args.moe_variant
        for row in run_moe_ab(args.requests, args.concurrency,
                              args.prompt_len, args.max_new, only=only):
            print(json.dumps(row), flush=True)
        raise SystemExit(0)
    if args.workload in ("quant", "longctx"):
        fn = run_quant_ab if args.workload == "quant" else run_longctx_ab
        rows = fn(args.requests, args.concurrency, args.prompt_len,
                  args.max_new, only=args.variant)
        if not rows:
            raise SystemExit(
                f"no variants ran for --workload {args.workload} "
                f"--variant {args.variant} on this backend")
        for row in rows:
            print(json.dumps(row), flush=True)
        raise SystemExit(0)
    wls = (["uniform", "mixed", "prefix"] if args.workload == "all"
           else [args.workload])
    for wl in wls:
        print(json.dumps(run_bench(wl, args.requests, args.concurrency,
                                   args.prompt_len, args.max_new,
                                   paged=args.paged)), flush=True)
