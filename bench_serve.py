"""Serving benchmark: continuous-batching req/s + TTFT/TPOT percentiles.

BASELINE config 2 evidence ("KServe req/s + p50 TTFT, v5e"): drives the
LLMEngine with a closed-loop client pool and prints one JSON line. The
driver's headline bench stays bench.py (training); run this by hand:

    python bench_serve.py [--requests 64] [--concurrency 16]
"""

from __future__ import annotations

import argparse
import json
import threading
import time


def run_bench(requests: int, concurrency: int, prompt_len: int,
              max_new: int, paged: bool = False) -> dict:
    import jax
    import numpy as np

    from kubeflow_tpu.core.serving import BatchingSpec
    from kubeflow_tpu.models.config import preset
    from kubeflow_tpu.serve.engine import LLMEngine, SamplingParams

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = preset(
            "llama3-8b",
            n_layers=8, hidden=2048, n_heads=32, n_kv_heads=8, head_dim=64,
            mlp_dim=8192, vocab_size=32000, max_seq_len=2048)
        model_tag = "llama3-0.6b"
    else:
        cfg = preset("tiny")
        model_tag = "tiny"
        prompt_len = min(prompt_len, 64)

    engine = LLMEngine(cfg, BatchingSpec(
        max_batch_size=min(16, concurrency), max_seq_len=cfg.max_seq_len,
        prefill_buckets=[prompt_len],
        paged=paged, page_size=128,
        weights_dtype="bfloat16" if on_tpu else None))
    engine.start()

    rng = np.random.default_rng(0)
    params = SamplingParams(max_new_tokens=max_new, temperature=0.0)
    results = []
    lock = threading.Lock()

    def client(n_requests: int):
        for _ in range(n_requests):
            prompt = rng.integers(1, cfg.vocab_size,
                                  size=prompt_len).tolist()
            t0 = time.perf_counter()
            req = engine.submit(prompt, params)
            first = None
            tokens = 0
            while True:
                tok = req.stream.get()
                if tok is None:
                    break
                tokens += 1
                if first is None:
                    first = time.perf_counter() - t0
            with lock:
                results.append((first, time.perf_counter() - t0, tokens))

    concurrency = max(1, min(concurrency, requests))
    # Distribute the remainder so exactly `requests` requests run.
    base, extra = divmod(requests, concurrency)
    counts = [base + (1 if i < extra else 0) for i in range(concurrency)]
    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,))
               for c in counts if c > 0]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    engine.stop()

    ttfts = sorted(r[0] for r in results if r[0] is not None)
    totals = [r[1] for r in results]
    tokens = sum(r[2] for r in results)
    p = lambda xs, q: xs[min(len(xs) - 1, int(q * len(xs)))]  # noqa: E731
    return {
        "metric": f"serve_req_per_sec[{model_tag},prompt{prompt_len},"
                  f"gen{max_new},c{concurrency}"
                  f"{',paged' if paged else ''}]",
        "value": round(len(results) / wall, 2),
        "unit": "req/s",
        "vs_baseline": 1.0,
        "detail": {
            "p50_ttft_ms": round(p(ttfts, 0.5) * 1e3, 1),
            "p99_ttft_ms": round(p(ttfts, 0.99) * 1e3, 1),
            "mean_total_ms": round(sum(totals) / len(totals) * 1e3, 1),
            "decode_tokens_per_sec": round(tokens / wall, 1),
            "requests": len(results),
        },
    }


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=512)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--paged", action="store_true",
                    help="paged KV cache + prefix caching engine")
    args = ap.parse_args()
    print(json.dumps(run_bench(args.requests, args.concurrency,
                               args.prompt_len, args.max_new,
                               paged=args.paged)))
